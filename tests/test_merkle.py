"""Device-Merkleized state (ops/merkle.py).

The numpy twin is the reference: build/update/prove/verify are pinned
here jax-free, and the jnp twin is asserted bit-identical against it —
the same twin discipline tests/test_exec.py applies to the root chain.
"""

import dataclasses

import numpy as np
import pytest

from hyperdrive_tpu.ops.merkle import (
    MAX_DEPTH,
    MerkleProof,
    NODE_WORDS,
    build_tree_np,
    combine_np,
    fold_merkle_np,
    fold_path_np,
    leaf_count,
    leaf_words_np,
    merkle_bytes,
    merkle_root_np,
    prove_np,
    tree_depth,
    update_tree_np,
    verify_inclusion,
)
from hyperdrive_tpu.ops.rootmix import fold_root_np, root_bytes, root_words

_SEED = 23


def _state(n, seed=_SEED):
    rng = np.random.default_rng(seed)
    bal = rng.integers(-1000, 100000, size=n, dtype=np.int32)
    stk = rng.integers(0, 500, size=n, dtype=np.int32)
    return bal, stk


# ------------------------------------------------------------ tree shape


def test_leaf_count_and_depth_follow_power_of_two_padding():
    assert [leaf_count(n) for n in (1, 2, 3, 16, 17, 64)] == [
        1, 2, 4, 16, 32, 64,
    ]
    assert [tree_depth(n) for n in (1, 2, 3, 16, 17, 64)] == [
        0, 1, 2, 4, 5, 6,
    ]


def test_build_tree_levels_halve_to_one_root():
    bal, stk = _state(20)  # pads to 32
    tree = build_tree_np(bal, stk)
    assert [lvl.shape for lvl in tree] == [
        (32, NODE_WORDS), (16, NODE_WORDS), (8, NODE_WORDS),
        (4, NODE_WORDS), (2, NODE_WORDS), (1, NODE_WORDS),
    ]
    assert merkle_root_np(tree).shape == (NODE_WORDS,)
    assert len(merkle_bytes(merkle_root_np(tree))) == 16


def test_combine_is_position_asymmetric():
    l, r = leaf_words_np(np.arange(2, dtype=np.uint32), [5, 9], [1, 2])
    assert not np.array_equal(
        combine_np(l[None], r[None]), combine_np(r[None], l[None])
    )


# --------------------------------------------------- incremental update


def test_incremental_update_matches_full_rebuild():
    bal, stk = _state(64)
    tree = build_tree_np(bal, stk)
    rng = np.random.default_rng(7)
    for _ in range(10):
        dirty = rng.integers(0, 64, size=6)
        bal[dirty] += rng.integers(1, 50, size=6, dtype=np.int32)
        stk[dirty[0]] += 1
        update_tree_np(tree, bal, stk, np.append(dirty, dirty[0]))
        ref = build_tree_np(bal, stk)
        for got, want in zip(tree, ref):
            assert np.array_equal(got, want)


def test_update_with_clean_and_duplicate_targets_is_idempotent():
    # The executors pass raw scatter targets (pad rows point at account
    # 0): recomputing a CLEAN leaf must be a no-op, so no mask or dedup
    # is ever needed for correctness.
    bal, stk = _state(16)
    tree = build_tree_np(bal, stk)
    before = [lvl.copy() for lvl in tree]
    update_tree_np(tree, bal, stk, np.array([0, 0, 3, 3, 15]))
    for got, want in zip(tree, before):
        assert np.array_equal(got, want)


def test_pad_leaves_are_stable_zero_accounts():
    # 20 accounts pad to 32: the 12 pad leaves are zero-balance
    # zero-stake accounts at their padded index, never dirtied — two
    # ledgers differing only in a pad-index write cannot exist, and the
    # tree equals a 32-account ledger whose tail is genuinely zero.
    bal, stk = _state(20)
    tree = build_tree_np(bal, stk)
    wide = build_tree_np(
        np.pad(bal, (0, 12)), np.pad(stk, (0, 12))
    )
    for got, want in zip(tree, wide):
        assert np.array_equal(got, want)


# ------------------------------------------------------- proofs + verify


def test_prove_then_fold_path_recovers_root_for_every_account():
    bal, stk = _state(20)
    tree = build_tree_np(bal, stk)
    root = merkle_root_np(tree)
    for account in range(20):
        sibs = prove_np(tree, account)
        assert len(sibs) == tree_depth(20) == 5
        leaf = leaf_words_np(
            np.asarray([account], dtype=np.uint32),
            [bal[account]], [stk[account]],
        )[0]
        assert np.array_equal(fold_path_np(leaf, account, sibs), root)


def _chained(bal, stk, height=3, seed=11):
    """A miniature chained root: fold_root(prev, h, fold_merkle(d, m))
    with an arbitrary digest — enough to test verify_inclusion without
    an executor."""
    rng = np.random.default_rng(seed)
    prev_words = rng.integers(0, 2**32, size=8, dtype=np.uint64).astype(
        np.uint32
    )
    prev = root_bytes(prev_words)
    digest = tuple(
        int(v) for v in rng.integers(0, 2**32, size=8, dtype=np.uint64)
    )
    tree = build_tree_np(bal, stk)
    folded = fold_merkle_np(
        np.asarray(digest, dtype=np.uint32), merkle_root_np(tree)
    )
    root = root_bytes(fold_root_np(root_words(prev), height, folded))
    return tree, prev, digest, root


def test_verify_inclusion_accepts_honest_proof():
    bal, stk = _state(16)
    tree, prev, digest, root = _chained(bal, stk)
    for account in (0, 7, 15):
        proof = MerkleProof(
            height=3, account=account, balance=int(bal[account]),
            stake=int(stk[account]), prev_root=prev, digest=digest,
            siblings=prove_np(tree, account),
        )
        assert verify_inclusion(
            root, account, proof.balance, proof.stake, proof
        )


def test_verify_inclusion_rejects_all_four_forgeries():
    bal, stk = _state(16)
    tree, prev, digest, root = _chained(bal, stk)
    proof = MerkleProof(
        height=3, account=7, balance=int(bal[7]), stake=int(stk[7]),
        prev_root=prev, digest=digest, siblings=prove_np(tree, 7),
    )
    stale_root = dataclasses.replace(proof, prev_root=b"\x01" * 32)
    forged_sib = dataclasses.replace(
        proof, siblings=((9, 9, 9, 9),) + proof.siblings[1:]
    )
    truncated = dataclasses.replace(proof, siblings=proof.siblings[:-1])
    wrong_leaf = dataclasses.replace(proof, balance=proof.balance + 1)
    for bad in (stale_root, forged_sib, truncated, wrong_leaf):
        assert not verify_inclusion(root, 7, bad.balance, bad.stake, bad)


def test_verify_inclusion_rejects_malformed_shapes():
    bal, stk = _state(16)
    tree, prev, digest, root = _chained(bal, stk)
    good = MerkleProof(
        height=3, account=7, balance=int(bal[7]), stake=int(stk[7]),
        prev_root=prev, digest=digest, siblings=prove_np(tree, 7),
    )
    assert not verify_inclusion(
        root, 7, good.balance, good.stake,
        dataclasses.replace(good, height=0),
    )
    assert not verify_inclusion(
        root, 7, good.balance, good.stake,
        dataclasses.replace(good, prev_root=b"\x00" * 8),
    )
    assert not verify_inclusion(
        root, 7, good.balance, good.stake,
        dataclasses.replace(good, digest=digest[:4]),
    )
    over = dataclasses.replace(
        good, siblings=good.siblings * (MAX_DEPTH // 4 + 1)
    )
    assert not verify_inclusion(root, 7, good.balance, good.stake, over)
    # Account index outside the path's span.
    assert not verify_inclusion(root, 1 << 10, good.balance, good.stake,
                                good)


# ------------------------------------------------------- jnp twin parity


def test_jax_twins_match_numpy_bitwise():
    jnp = pytest.importorskip("jax.numpy")
    from hyperdrive_tpu.ops.merkle import (
        build_tree_jax,
        fold_merkle_jax,
        update_tree_jax,
    )

    bal, stk = _state(20)
    ref = build_tree_np(bal, stk)
    dtree = build_tree_jax(jnp.asarray(bal), jnp.asarray(stk))
    for got, want in zip(dtree, ref):
        assert np.array_equal(np.asarray(got), want)

    dirty = np.array([0, 3, 3, 19, 7], dtype=np.int32)
    bal[dirty] += 9
    update_tree_np(ref, bal, stk, dirty)
    dtree = update_tree_jax(
        dtree, jnp.asarray(bal), jnp.asarray(stk), jnp.asarray(dirty)
    )
    for got, want in zip(dtree, ref):
        assert np.array_equal(np.asarray(got), want)

    digest = np.arange(8, dtype=np.uint32) * np.uint32(0x9E3779B9)
    want = fold_merkle_np(digest, merkle_root_np(ref))
    got = fold_merkle_jax(jnp.asarray(digest), dtree[-1][0])
    assert np.array_equal(np.asarray(got), want)
