"""Integration scenarios through the deterministic simulator.

Mirrors the reference's replica_test.go scenario set: 3f+1 honest, exactly
2f+1 online, f killed mid-run, f Byzantine, sub-quorum stall, and
deterministic record/replay of a full run.
"""

import os

import pytest

from hyperdrive_tpu.harness import (
    ScenarioRecord,
    Simulation,
    VirtualClock,
)


def test_honest_network_reaches_target_height():
    # Reference: "3f+1 honest replicas reach consensus to height 30"
    # (replica_test.go:384-430).
    sim = Simulation(n=10, target_height=30, seed=7)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights} after {res.steps} steps"
    res.assert_safety()
    # Every replica committed every height 1..30 with identical values.
    for c in res.commits:
        assert set(range(1, 31)) <= set(c.keys())


def test_exactly_two_f_plus_one_online():
    # Reference: replica_test.go:452-498 — progress with the bare quorum.
    # Offline proposers force propose-timeouts and multi-round heights.
    sim = Simulation(n=10, target_height=10, seed=11, offline={7, 8, 9})
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    for i in (7, 8, 9):
        assert not res.commits[i]


def test_f_replicas_killed_mid_run():
    # Reference: replica_test.go:521-592 — f random deaths mid-run still
    # leave 2f+1, so the network keeps committing.
    sim = Simulation(
        n=10,
        target_height=10,
        seed=13,
        kill_at_step={2: 200, 5: 350, 8: 500},
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()


def test_f_byzantine_proposers():
    # Reference: replica_test.go:615-672 — f replicas propose garbage;
    # honest replicas prevote nil on those rounds and consensus survives.
    byz = {
        i: (lambda h, r, i=i: bytes([i]) * 32) for i in (0, 1, 2)
    }
    sim = Simulation(
        n=10, target_height=8, seed=17, byzantine_proposer=byz
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    # Byzantine junk must never be committed by honest replicas unless it
    # won honestly (a byzantine proposer CAN have its value committed if it
    # behaves; the invariant is only agreement).


def test_sub_quorum_network_stalls():
    # Reference: replica_test.go:684-746 — fewer than 2f+1 online must
    # never commit anything.
    sim = Simulation(n=10, target_height=3, seed=19, offline={6, 7, 8, 9})
    res = sim.run(max_steps=40_000)
    assert not res.completed
    for c, _alive in zip(res.commits, res.alive):
        assert not c  # nothing can ever commit below quorum
    res.assert_safety()


def test_death_below_quorum_stalls_from_that_height():
    # Reference: replica_test.go:748-847 — killing one replica of a bare
    # 2f+1 quorum freezes progress at (or just after) the kill point.
    sim = Simulation(
        n=10,
        target_height=50,
        seed=23,
        offline={7, 8, 9},
        kill_at_step={6: 800},
    )
    res = sim.run(max_steps=60_000)
    assert not res.completed
    res.assert_safety()


def test_adversarial_reorder_preserves_safety():
    # Reference: config[2] of BASELINE.md — adversarial mq reorder plus
    # timer timeouts; reordering slows progress but must never fork.
    sim = Simulation(n=10, target_height=10, seed=29, reorder=True)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()


def test_message_drops_never_violate_safety():
    # Liveness legitimately depends on eventual delivery (the protocol has
    # no retransmission; lagging replicas need ResetHeight resync), so a
    # lossy network MAY stall — but it must never fork.
    for seed in (31, 32, 33):
        sim = Simulation(n=4, target_height=5, seed=seed, drop_rate=0.05)
        res = sim.run(max_steps=50_000)
        res.assert_safety()


def test_record_replay_is_deterministic(tmp_path):
    # Reference: Scenario dump + REPLAY_MODE (replica_test.go:850-928,
    # 1049-1078): a recorded interleaving replays to the same commits.
    sim = Simulation(n=7, target_height=6, seed=37, reorder=True)
    res = sim.run()
    assert res.completed
    res.assert_safety()

    path = os.path.join(tmp_path, "failure.dump")
    res.record.dump(path)
    loaded = ScenarioRecord.load(path)
    assert loaded.seed == 37
    assert loaded.n == 7
    assert loaded.signatories == res.record.signatories
    assert len(loaded.messages) == len(res.record.messages)

    replayed = Simulation.replay(loaded)
    assert replayed.commits == res.commits
    assert replayed.heights == res.heights


def test_same_seed_same_run():
    a = Simulation(n=7, target_height=5, seed=41, reorder=True).run()
    b = Simulation(n=7, target_height=5, seed=41, reorder=True).run()
    assert a.commits == b.commits
    assert a.steps == b.steps
    assert a.virtual_time == b.virtual_time


def test_equivocation_is_caught_by_honest_replicas():
    # A Byzantine proposer that signs two different proposals for the same
    # (height, round): simulate by injecting the second propose directly.
    from hyperdrive_tpu.messages import Propose

    sim = Simulation(n=4, target_height=2, seed=43)
    for i, r in enumerate(sim.replicas):
        if sim.alive[i]:
            r.start()
    # Let the first proposer's legitimate propose reach replica 0 first.
    first_round_proposer = sim.replicas[0].proc.scheduler.schedule(1, 0)
    legit = None
    while sim.queue:
        to, msg = sim.queue.pop(0)
        sim.replicas[to].handle(msg)
        if isinstance(msg, Propose) and to == 0:
            legit = msg
            break
    assert legit is not None
    double = Propose(
        height=legit.height,
        round=legit.round,
        valid_round=legit.valid_round,
        value=b"\xde" * 32,
        sender=legit.sender,
    )
    sim.replicas[0].handle(double)
    assert ("double_propose", 0) in sim.caught


def test_signed_consensus_end_to_end():
    # Authenticated mode: every broadcast carries an Ed25519 signature and
    # every replica verifies before dispatch (BASELINE config 4's host
    # baseline, at miniature scale).
    sim = Simulation(n=4, target_height=3, seed=47, sign=True)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()


def test_signed_scenario_replays_with_signatures(tmp_path):
    import os

    sim = Simulation(n=4, target_height=2, seed=53, sign=True)
    res = sim.run()
    assert res.completed
    path = os.path.join(tmp_path, "signed.dump")
    res.record.dump(path)
    loaded = ScenarioRecord.load(path)
    replayed = Simulation.replay(loaded, sign=True)
    assert replayed.commits == res.commits


def test_forged_signature_blocks_vote():
    from hyperdrive_tpu.messages import Prevote

    sim = Simulation(n=4, target_height=2, seed=59, sign=True)
    for _i, r in enumerate(sim.replicas):
        r.start()
    # Inject a vote with a forged signature from a legitimate sender.
    forged = Prevote(
        height=1, round=0, value=b"\x42" * 32, sender=sim.signatories[1]
    ).with_signature(b"\x00" * 64)
    sim.replicas[0].handle(forged)
    assert sim.signatories[1] not in sim.replicas[0].proc.state.prevote_logs.get(0, {})


# --------------------------------------------------------------- burst mode
#
# Superstep delivery + aggregated verification (the batched replica driving
# mode behind BASELINE config 4). Same safety/liveness obligations as the
# lock-step scenarios above, plus exact replay of recorded burst boundaries.


def test_burst_honest_network_completes():
    sim = Simulation(n=10, target_height=15, seed=61, burst=True)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights} after {res.steps} steps"
    res.assert_safety()
    assert res.record.bursts and sum(res.record.bursts) == len(res.record.messages)
    for c in res.commits:
        assert set(range(1, 16)) <= set(c.keys())


def test_burst_with_faults_and_reorder():
    # Offline proposers force timeout rounds; reorder shuffles within each
    # superstep; a kill mid-run must not break safety.
    sim = Simulation(
        n=10,
        target_height=8,
        seed=67,
        burst=True,
        reorder=True,
        offline={8, 9},
        kill_at_step={7: 400},
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    # Kills apply at superstep boundaries, so every recorded delivery was
    # also settled — the record must replay to identical commits even
    # though replay has no kill/offline knowledge for dead replicas.
    replayed = Simulation.replay(res.record, offline={8, 9})
    assert replayed.commits == res.commits


def test_burst_signed_aggregated_host_verifier():
    # sign=True + burst: every window in the network is verified through
    # ONE aggregated HostVerifier launch per settle pass.
    sim = Simulation(n=4, target_height=4, seed=71, sign=True, burst=True)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()


def test_burst_record_replays_exactly(tmp_path):
    sim = Simulation(n=7, target_height=5, seed=73, burst=True, reorder=True)
    res = sim.run()
    assert res.completed
    path = os.path.join(tmp_path, "burst.dump")
    res.record.dump(path)
    loaded = ScenarioRecord.load(path)
    assert loaded.bursts == res.record.bursts
    replayed = Simulation.replay(loaded)
    assert replayed.commits == res.commits
    assert replayed.heights == res.heights


def test_burst_differential_modes_agree_and_replay_preserves_mode(tmp_path):
    # batch_ingest=False is the differential mode: same superstep windows,
    # per-message dispatch. Both modes must commit safely, and a dumped
    # record must replay under ITS OWN ingestion mode (a per-message record
    # silently replayed batched could diverge in schedules/evidence).
    batched = Simulation(n=7, target_height=5, seed=79, burst=True).run()
    serial = Simulation(
        n=7, target_height=5, seed=79, burst=True, batch_ingest=False
    ).run()
    assert batched.completed and serial.completed
    batched.assert_safety()
    serial.assert_safety()

    path = os.path.join(tmp_path, "serial.dump")
    serial.record.dump(path)
    loaded = ScenarioRecord.load(path)
    assert loaded.batch_ingest is False
    replayed = Simulation.replay(loaded)
    assert replayed.commits == serial.commits
    assert replayed.heights == serial.heights


def test_recorded_messages_list_compatibility():
    # The broadcast-compact delivery log must behave exactly like the
    # flat per-delivery list every consumer assumes: length accounting,
    # indexing/slicing, iteration, equality against plain lists (loaded
    # dumps), and appends remaining consistent after materialization.
    from hyperdrive_tpu.harness.sim import RecordedMessages

    log = RecordedMessages()
    log.append((3, "t0"))
    log.append_broadcast("b0", [0, 1, 2])
    log.append((1, "t1"))
    expect = [(3, "t0"), (0, "b0"), (1, "b0"), (2, "b0"), (1, "t1")]
    assert len(log) == 5
    assert log == expect and not log != expect
    assert log[1] == (0, "b0")
    assert log[1:4] == expect[1:4]
    assert list(log) == expect
    # Appends after the flat view exists stay visible and consistent.
    log.append_broadcast("b1", [2, 0])
    log.append((0, "t2"))
    expect += [(2, "b1"), (0, "b1"), (0, "t2")]
    assert len(log) == 8
    assert log == expect
    other = RecordedMessages()
    for to, m in expect:
        other.append((to, m))
    assert log == other


def test_shared_superstep_is_delivery_for_delivery_identical():
    # The shared-superstep fast path (one queue entry / one sort / one
    # verify per broadcast) must reproduce the per-delivery burst path
    # EXACTLY: same step count, same recorded delivery stream, same burst
    # boundaries, same commits — trajectory equality, not just agreement.
    kw = dict(n=7, target_height=6, seed=83, burst=True, sign=True)
    fast = Simulation(**kw)
    assert fast._shared_mode
    fres = fast.run()
    slow = Simulation(**kw, shared_superstep=False)
    assert not slow._shared_mode
    sres = slow.run()
    assert fres.completed and sres.completed
    assert fres.steps == sres.steps
    assert fres.virtual_time == sres.virtual_time
    assert fres.commits == sres.commits
    assert fres.record.bursts == sres.record.bursts
    assert fres.record.messages == sres.record.messages
    fres.assert_safety()


def test_shared_superstep_identical_under_tight_lane_capacity():
    # Near max_capacity the two burst paths must still agree delivery for
    # delivery: the shared lane applies the per-sender fast-lane cap
    # height-aware at settle time, exactly as delivery-time accounting
    # would (a commit-boundary superstep mixes heights, so a height-blind
    # cap would drop different messages than the per-delivery path).
    kw = dict(n=4, target_height=4, seed=87, burst=True, max_capacity=2)
    fres = Simulation(**kw).run(max_steps=100_000)
    sres = Simulation(**kw, shared_superstep=False).run(max_steps=100_000)
    assert fres.steps == sres.steps
    assert fres.commits == sres.commits
    assert fres.record.messages == sres.record.messages
    fres.assert_safety()


def test_shared_superstep_rejected_under_per_delivery_adversary():
    with pytest.raises(ValueError):
        Simulation(n=4, target_height=2, seed=1, burst=True, reorder=True,
                   shared_superstep=True)


def test_device_tally_matches_host_and_is_exercised():
    # The north-star integration: quorum counts come from the device vote
    # grid. CheckedTallyView raises on any device/host count mismatch, and
    # the hit counter proves the cascade actually consumed device counts.
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    views = []

    def check(view, proc):
        v = CheckedTallyView(view, proc)
        views.append(v)
        return v

    host = Simulation(n=7, target_height=5, seed=91, burst=True).run()
    dev = Simulation(
        n=7, target_height=5, seed=91, burst=True,
        device_tally=True, tally_check=check,
    ).run()
    assert host.completed and dev.completed
    dev.assert_safety()
    assert dev.commits == host.commits
    assert dev.heights == host.heights
    assert dev.steps == host.steps
    assert sum(v.hits for v in views) > 0, "device counts never consulted"


def test_device_tally_adversarial_differential():
    # Timeout rounds (offline proposers), reorder, and a mid-run kill push
    # the grid through resets, nil quorums, and round slots > 0 — every
    # count still checked equal to the host counters.
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    kw = dict(n=10, target_height=8, seed=67, burst=True, reorder=True,
              offline={8, 9}, kill_at_step={7: 400})
    host = Simulation(**kw).run()
    dev = Simulation(
        **kw, device_tally=True, tally_check=CheckedTallyView
    ).run()
    assert host.completed and dev.completed
    dev.assert_safety()
    assert dev.commits == host.commits


def test_device_tally_negative_round_vote_is_not_scattered():
    # Regression: vote inserts (unlike propose inserts) accept negative
    # rounds, and a slot of -1 flattens into the PREVIOUS plane's last
    # slot (e.g. replica 1's round -1 prevote lands in replica 0's
    # precommit slot R-1) — a phantom vote that could tip a quorum.
    import numpy as np

    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    sim = Simulation(n=4, target_height=3, seed=111, burst=True,
                     device_tally=True, tally_check=CheckedTallyView)
    sim.replicas[1].handle(
        Prevote(height=1, round=-1, value=b"\x77" * 32,
                sender=sim.signatories[3])
    )
    sim._settle()
    # The host log accepted the vote (parity with the reference's inserts,
    # which height-check but not round-check votes)...
    assert -1 in sim.replicas[1].proc.state.prevote_logs
    # ...but nothing was scattered: the device grid holds no vote at all,
    # phantom or otherwise.
    assert np.asarray(sim.vote_grid._present).sum() == 0


def test_device_tally_signed_full_pipeline(tmp_path):
    # Signatures + aggregated verification + device tallies: the grid only
    # sees verified survivors (fused behind the verification mask). The
    # record replays bit-identically WITHOUT a grid, because device counts
    # equal host counts wherever they are used.
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    dev = Simulation(
        n=4, target_height=4, seed=71, sign=True, burst=True,
        device_tally=True, tally_check=CheckedTallyView,
    ).run()
    assert dev.completed
    dev.assert_safety()
    path = os.path.join(tmp_path, "devtally.dump")
    dev.record.dump(path)
    replayed = Simulation.replay(ScenarioRecord.load(path), sign=True)
    assert replayed.commits == dev.commits
    assert replayed.heights == dev.heights


@pytest.mark.requires_shard_map
def test_device_tally_sharded_mesh_consensus():
    # Sharded CONSENSUS on the 8-device virtual mesh: the vote grid's
    # validator axis is split across devices, every settle's quorum counts
    # psum over the mesh, and the rule cascade consumes them — with
    # CheckedTallyView asserting device==host count-for-count, and the
    # run trajectory-identical to the single-chip grid and the host run.
    import jax

    from hyperdrive_tpu.ops.votegrid import CheckedTallyView
    from hyperdrive_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    mesh = make_mesh(devices=jax.devices()[:8], hr=1)

    views = []

    def check(view, proc):
        v = CheckedTallyView(view, proc)
        views.append(v)
        return v

    kw = dict(n=8, target_height=4, seed=201, sign=True, burst=True)
    sharded = Simulation(
        **kw, device_tally=True, tally_mesh=mesh, tally_check=check
    ).run()
    assert sharded.completed
    sharded.assert_safety()
    assert sum(v.hits for v in views) > 0, "sharded counts never consulted"

    single = Simulation(
        **kw, device_tally=True, tally_check=CheckedTallyView
    ).run()
    host = Simulation(**kw).run()
    assert sharded.commits == single.commits == host.commits
    assert sharded.steps == single.steps == host.steps


@pytest.mark.parametrize(
    "n,target,seed,sign,max_steps",
    [
        # Unsigned point: isolates sharded-grid correctness from the
        # signature pipeline (so a 512-scale failure is attributable).
        pytest.param(512, 2, 71, False, 50_000_000, id="512-unsigned"),
        # Signed points: signature pipeline + sharded grid + automaton
        # composed at scale (VERDICT r4 #4). At 1024 the grid alone is
        # ~277 MB at R=4 (4x BENCH.md config 7's grid_bytes_sim_512
        # row — published there as grid_bytes_sim_1024), so one height
        # bounds the wall time.
        pytest.param(512, 2, 71, True, 50_000_000, id="512-signed"),
        pytest.param(1024, 1, 72, True, 100_000_000, id="1024-signed"),
    ],
)
@pytest.mark.requires_shard_map
def test_device_tally_sharded_at_scale(n, target, seed, sign, max_steps):
    # The >256-validator operating points (SURVEY §5's scaling story):
    # the vote grid's validator axis sharded 8 ways drives a full
    # n-replica consensus with every device-sourced count checked equal
    # to the host counters and the commit maps identical to a pure host
    # run.
    import jax

    from hyperdrive_tpu.ops.votegrid import CheckedTallyView
    from hyperdrive_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    mesh = make_mesh(devices=jax.devices()[:8], hr=1)
    kw = dict(n=n, target_height=target, seed=seed, burst=True, sign=sign)
    sharded = Simulation(
        **kw, device_tally=True, tally_mesh=mesh,
        tally_check=CheckedTallyView,
    ).run(max_steps=max_steps)
    assert sharded.completed, f"stalled at {sharded.heights}"
    sharded.assert_safety()
    host = Simulation(**kw).run(max_steps=max_steps)
    assert sharded.commits == host.commits
    assert sharded.steps == host.steps


def test_device_tally_fused_single_launch_pipeline():
    # The fused settle: Ed25519 verification + grid scatter + tally in ONE
    # launch (TpuBatchVerifier exposes its traceable kernel; the grid
    # embeds it). Every device count still checked equal to the host
    # counters, and the run must be trajectory-identical to the unfused
    # device-tally run AND the plain host run.
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView
    from hyperdrive_tpu.verifier import HostVerifier

    views = []

    def check(view, proc):
        v = CheckedTallyView(view, proc)
        views.append(v)
        return v

    kw = dict(n=4, target_height=4, seed=171, sign=True, burst=True)
    ver = TpuBatchVerifier(buckets=(64, 256))
    fused = Simulation(
        **kw, batch_verifier=ver, dedup_verify=True,
        device_tally=True, tally_check=check,
    )
    assert fused._fused_ok
    fres = fused.run()
    assert fres.completed
    fres.assert_safety()
    assert fused.vote_grid._fused, "fused launcher never compiled"
    assert sum(v.hits for v in views) > 0, "device counts never consulted"

    unfused = Simulation(
        **kw, batch_verifier=HostVerifier(), dedup_verify=True,
        device_tally=True, tally_check=CheckedTallyView,
    ).run()
    host = Simulation(
        **kw, batch_verifier=HostVerifier(), dedup_verify=True
    ).run()
    assert fres.commits == unfused.commits == host.commits
    assert fres.steps == unfused.steps == host.steps
    assert fres.record.messages == unfused.record.messages


def test_fused_engages_when_network_exceeds_per_sender_capacity():
    # Regression (BENCH.md config 8's 1024-storm diagnosis): when the
    # superstep's shared lane exceeds max_capacity but the PER-SENDER cap
    # drops nothing (n senders, one broadcast each — every network larger
    # than max_capacity validators), the capped window must stay the
    # shared list ITSELF. A copy here broke the fused settle's identity
    # eligibility and silently demoted >1000-validator lockstep settles
    # to the two-launch path.
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    kw = dict(n=16, target_height=2, seed=91, sign=True, burst=True,
              max_capacity=8)  # shared lane (16 votes) > cap, 0 dropped
    fused = Simulation(
        **kw,
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
        dedup_verify=True,
        device_tally=True,
    )
    fres = fused.run()
    assert fres.completed, f"stalled at {fres.heights}"
    fres.assert_safety()
    hists = fused.tracer.snapshot()["histograms"]
    assert hists.get("sim.fused.sync.latency", {}).get("count", 0) > 0, (
        "capacity-capped lockstep settle never fused"
    )
    host = Simulation(**kw).run()
    assert fres.commits == host.commits
    assert fres.steps == host.steps


def test_routed_tally_protects_serialized_reorder_settles():
    # Regression (BENCH.md config 8's adversarial negative): under
    # adversarial reorder the shared superstep is off and settle windows
    # collapse to 1-2 messages; the crossover router must protect the
    # UNFUSED device-tally path too — tiny settles dispatch on host with
    # the grid poisoned, paying zero grid round trips, trajectory
    # identical to the host run.
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    kw = dict(n=7, target_height=2, seed=93, sign=True, burst=True,
              reorder=True)
    routed = Simulation(
        **kw,
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
        dedup_verify=True,
        device_tally=True,
        fused_min_window=10_000,
    )
    rres = routed.run(max_steps=2_000_000)
    assert rres.completed, f"stalled at {rres.heights}"
    rres.assert_safety()
    hists = routed.tracer.snapshot()["histograms"]
    assert hists["sim.settle.host_routed"]["count"] > 0
    assert "sim.tally.launch" not in hists, (
        "a sub-crossover reorder settle still paid a grid launch"
    )
    host = Simulation(**kw).run(max_steps=2_000_000)
    assert rres.commits == host.commits
    assert rres.steps == host.steps


def test_fused_min_window_routes_every_settle_to_host():
    # Crossover routing, threshold above any window: no fused launch ever
    # fires, every settle is handled on host — and the run is trajectory-
    # identical to both the plain host run and the always-fused run.
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    kw = dict(n=4, target_height=3, seed=83, sign=True, burst=True)
    routed = Simulation(
        **kw,
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
        dedup_verify=True,
        device_tally=True,
        fused_min_window=10_000,
    )
    rres = routed.run()
    assert rres.completed, f"stalled at {rres.heights}"
    rres.assert_safety()
    hists = routed.tracer.snapshot()["histograms"]
    assert "sim.fused.sync.latency" not in hists, "a fused launch still fired"
    assert hists["sim.settle.host_routed"]["count"] > 0
    host = Simulation(**kw).run()
    fused = Simulation(
        **kw,
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
        dedup_verify=True,
        device_tally=True,
    ).run()
    assert rres.commits == host.commits == fused.commits
    assert rres.steps == host.steps == fused.steps


def test_fused_min_window_partial_grid_poison_is_sound():
    # A mid threshold leaves SOME settles fused and SOME host-routed: the
    # grid is then missing the routed settles' votes, and the poison
    # (whole-height dirty marks) must keep the cascade off those counts.
    # CheckedTallyView raises on any device/host count divergence, and
    # the run must still commit identically to the host run.
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView

    views = []

    def checked(view, proc):
        v = CheckedTallyView(view, proc)
        views.append(v)
        return v

    kw = dict(n=4, target_height=4, seed=83, sign=True, burst=True)
    host = Simulation(**kw).run()
    for threshold in (3, 5, 7):
        views.clear()
        sim = Simulation(
            **kw,
            batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
            dedup_verify=True,
            device_tally=True,
            fused_min_window=threshold,
            tally_check=checked,
        )
        res = sim.run()
        assert res.completed, f"threshold {threshold}: {res.heights}"
        res.assert_safety()
        assert res.commits == host.commits, f"threshold {threshold}"
        assert res.steps == host.steps, f"threshold {threshold}"
        hists = sim.tracer.snapshot()["histograms"]
        assert hists["sim.settle.host_routed"]["count"] > 0, threshold
        if threshold == 3:
            # At this seed/size, threshold 3 leaves a genuine MIX: some
            # settles fused (grid engaged), some routed (grid poisoned) —
            # the combination the poison logic exists for.
            assert hists["sim.fused.sync.latency"]["count"] > 0


def test_burst_signed_with_tpu_batch_verifier():
    # The full BASELINE config-4 pipeline at miniature scale: a signed
    # burst-mode network whose aggregated windows are verified by the
    # device kernel (CPU backend under tests; same code path as TPU).
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    sim = Simulation(
        n=4,
        target_height=3,
        seed=79,
        sign=True,
        burst=True,
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)),
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    for c in res.commits:
        assert set(range(1, 4)) <= set(c.keys())


def test_burst_signed_device_verify_forced_for_small_windows():
    # At miniature scale (n=4) every non-fused settle window is under the
    # 64-item host-routing threshold, so the auto small-window routing
    # would send ALL of them to HostVerifier and the device verify path
    # would go unexercised end to end. small_window_host=False pins the
    # device backend for every window, however small, and the run must be
    # trajectory-identical to the auto-routed one (verdicts are
    # differentially equal by construction).
    from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

    kw = dict(n=4, target_height=3, seed=79, sign=True, burst=True)
    forced_verifier = TpuBatchVerifier(buckets=(16, 64))
    forced = Simulation(
        batch_verifier=forced_verifier,
        small_window_host=False,
        **kw,
    )
    assert forced._small_win_host is None
    fres = forced.run()
    assert fres.completed, f"stalled at {fres.heights}"
    fres.assert_safety()
    auto = Simulation(
        batch_verifier=TpuBatchVerifier(buckets=(16, 64)), **kw
    )
    assert auto._small_win_host is not None
    ares = auto.run()
    assert fres.commits == ares.commits
    assert fres.steps == ares.steps


# ------------------------------------------------------- MPC payloads
#
# BASELINE config 5's capability: proposals carry (2f+1)-of-n Shamir share
# bundles; every commit reconstructs the payload on device and checks it
# against the value's commitment.


def test_payload_commit_reconstructs_on_all_replicas():
    sim = Simulation(n=4, target_height=5, seed=97, payload_bytes=62)
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    for i in range(4):
        assert set(sim.reconstructed[i]) >= set(range(1, 6))
        for h, payload in sim.reconstructed[i].items():
            # The reconstructed bytes must be exactly the payload the
            # replica's own committed value commits to.
            assert payload == sim._payload_for_value(sim.commits[i][h])
            assert len(payload) == 62


def test_payload_pinned_device_reconstruction():
    # Commit payloads route to the host by default (AdaptiveReconstructor
    # — commit batches sit far below any device launch's worth), so pin
    # one e2e run to the device kernel to keep that path exercised end to
    # end.
    from hyperdrive_tpu.ops.shamir import BatchReconstructor

    rec = BatchReconstructor()
    sim = Simulation(
        n=4, target_height=3, seed=97, payload_bytes=62, reconstructor=rec
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    assert rec._lam_cache, "device kernel never launched"
    host_sim = Simulation(n=4, target_height=3, seed=97, payload_bytes=62)
    hres = host_sim.run()
    assert hres.completed
    assert not host_sim.reconstructor.device._lam_cache  # host-routed
    for i in range(4):
        assert sim.reconstructed[i] == host_sim.reconstructed[i]


def test_payload_burst_per_replica_reconstruction():
    # No dedup: every replica reconstructs every commit itself.
    sim = Simulation(
        n=4,
        target_height=3,
        seed=101,
        payload_bytes=31,
        burst=True,
        dedup_reconstruct=False,
    )
    res = sim.run()
    assert res.completed
    for i in range(4):
        assert set(sim.reconstructed[i]) >= {1, 2, 3}


def test_payload_tampered_bundle_is_invalid():
    # A proposal whose payload is not the bundle its value commits to must
    # be logged invalid (prevote nil), exactly like a garbage value.
    from dataclasses import replace as dc_replace

    from hyperdrive_tpu.messages import Propose

    sim = Simulation(n=4, target_height=2, seed=103, payload_bytes=31)
    for _i, r in enumerate(sim.replicas):
        r.start()
    legit = None
    while sim.queue:
        to, msg = sim.queue.pop(0)
        if isinstance(msg, Propose) and to == 1:
            legit = msg
            break
        sim.replicas[to].handle(msg)
    assert legit is not None and legit.payload
    tampered = dc_replace(legit, payload=legit.payload[:-1] + b"\x00")
    sim.replicas[1].handle(tampered)
    assert sim.replicas[1].proc.state.propose_is_valid.get(legit.round) is False


def test_payload_survives_signed_mode():
    # Payload + signatures together: the digest binds the bundle, so the
    # signed path verifies and the run completes with reconstruction.
    sim = Simulation(
        n=4, target_height=3, seed=107, payload_bytes=31, sign=True
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    for i in range(4):
        assert set(sim.reconstructed[i]) >= {1, 2, 3}


def test_burst_rejects_byzantine_signer():
    # A sender whose signatures never verify: everyone else must still
    # reach consensus, and the bad sender's votes must never enter logs.
    from hyperdrive_tpu.verifier import HostVerifier

    class RejectSender(HostVerifier):
        def __init__(self, bad_pub):
            super().__init__()
            self.bad = bad_pub

        def verify_signatures(self, items):
            mask = super().verify_signatures(items)
            for j, (pub, _, _) in enumerate(items):
                if pub == self.bad:
                    mask[j] = False
            return mask

    probe = Simulation(n=4, target_height=1, seed=83, sign=True)
    bad = probe.signatories[3]
    sim = Simulation(
        n=4,
        target_height=3,
        seed=83,
        sign=True,
        burst=True,
        batch_verifier=RejectSender(bad),
    )
    res = sim.run()
    assert res.completed, f"stalled at {res.heights}"
    res.assert_safety()
    for r in sim.replicas:
        for logs in r.proc.state.prevote_logs.values():
            assert bad not in logs


def test_crash_restore_rejoin_from_checkpoint(tmp_path):
    # The full crash-recovery story (reference contract: "State should be
    # saved after every method call", process/state.go:18-20; death
    # scenarios, replica_test.go:748-847): a replica checkpoints on every
    # commit, dies mid-run, is restored from its checkpoint FILE, rejoins
    # via reset_height, and the network completes with safety intact.
    from hyperdrive_tpu.replica import ResetHeight
    from hyperdrive_tpu.utils.checkpoint import restore_process, save_process

    victim = 3
    ckpt = os.path.join(tmp_path, "victim.ckpt")
    sim = Simulation(n=7, target_height=8, seed=131, sign=True,
                     kill_at_step={victim: 400})
    orig = sim._on_commit

    def commit_and_checkpoint(i, height, value):
        out = orig(i, height, value)
        if i == victim:
            save_process(sim.replicas[victim].proc, ckpt)
        return out

    sim._on_commit = commit_and_checkpoint
    res = sim.run(max_steps=500_000)
    # Phase 1: the survivors (still a quorum) finished without the victim.
    assert res.completed
    assert not sim.alive[victim]
    dead_height = sim.replicas[victim].current_height()
    assert dead_height < 8

    # Phase 2: restart the victim from its checkpoint file. The restored
    # process is at the height of its last pre-crash commit...
    restore_process(sim.replicas[victim].proc, ckpt)
    restored_h = sim.replicas[victim].current_height()
    assert 1 < restored_h <= dead_height
    # ...rejoins via the resync mechanism, and catches up to the network.
    sim.alive[victim] = True
    net_height = max(c and max(c) or 0 for c in sim.commits) + 1
    sim.replicas[victim].handle(ResetHeight(height=net_height))
    sim.target_height = 12
    sim._pending_replicas = {i for i in range(sim.n) if sim.alive[i]}
    res2 = sim.run(max_steps=500_000, start=False)
    assert res2.completed, f"rejoined network stalled at {res2.heights}"
    res2.assert_safety()
    # The revived replica committed every height from its rejoin point on.
    revived = sim.commits[victim]
    for h in range(net_height, 13):
        assert h in revived


def test_record_replay_with_timeouts(tmp_path):
    # Regression: dumps containing Timeout deliveries (any run that
    # exercises liveness — offline proposers force propose timeouts)
    # failed to LOAD because message interning read msg.signature, which
    # Timeout events do not carry. Exactly the runs worth replaying.
    # Replica 1 proposes height 1 round 0 ((h+r) % n), so taking it
    # offline forces a propose timeout immediately.
    sim = Simulation(n=4, target_height=3, seed=91, offline={1})
    res = sim.run(max_steps=200_000)
    assert res.completed
    res.assert_safety()
    from hyperdrive_tpu.messages import Timeout

    assert any(isinstance(m, Timeout) for _, m in res.record.messages)

    path = os.path.join(tmp_path, "timeouts.dump")
    res.record.dump(path)
    replayed = Simulation.replay(ScenarioRecord.load(path))
    assert replayed.commits == res.commits
    assert replayed.heights == res.heights


def test_record_false_runs_without_recorder():
    # Long benchmark runs opt out of the replay recorder (its delivered-
    # message list dominates memory at depth); semantics are unchanged
    # and the result says so loudly via record=None.
    on = Simulation(n=4, target_height=5, seed=23)
    off = Simulation(n=4, target_height=5, seed=23, record=False)
    r_on, r_off = on.run(), off.run()
    assert r_on.completed and r_off.completed
    assert r_off.commits == r_on.commits
    assert r_off.record is None
    assert not off.record.messages  # nothing was retained

    bon = Simulation(n=4, target_height=5, seed=23, burst=True)
    boff = Simulation(n=4, target_height=5, seed=23, burst=True, record=False)
    b_on, b_off = bon.run(), boff.run()
    assert b_off.commits == b_on.commits
    assert b_off.record is None and not boff.record.bursts


# ----------------------------------------------------------------- clock


class TestVirtualClockPrune:
    # Edge cases of the heap pruning the driver leans on during long
    # runs (ISSUE 5 satellite). Events here are plain strings; prune's
    # predicate sees the event, never the deadline.

    def test_prune_empty_heap_is_a_noop(self):
        clock = VirtualClock()
        assert clock.prune(lambda e: True) == 0
        assert clock.prune(lambda e: False) == 0
        assert clock.pending() == 0 and clock.now == 0.0

    def test_prune_keep_all_drops_nothing(self):
        clock = VirtualClock()
        for delay, name in [(1.0, "a"), (2.0, "b"), (3.0, "c")]:
            clock.schedule(delay, name, None)
        assert clock.prune(lambda e: True) == 0
        assert clock.pending() == 3
        event, _ = clock.fire_next()
        assert event == "a" and clock.now == 1.0

    def test_partial_prune_preserves_heap_order(self):
        clock = VirtualClock()
        for delay, name in [(1.0, "a"), (2.0, "b"), (3.0, "c")]:
            clock.schedule(delay, name, None)
        event, _ = clock.fire_next()
        assert event == "a"
        assert clock.prune(lambda e: e != "b") == 1
        assert clock.pending() == 1
        event, _ = clock.fire_next()
        assert event == "c" and clock.now == 3.0

    def test_prune_everything_empties_the_heap(self):
        clock = VirtualClock()
        for i in range(17):
            clock.schedule(float(i + 1), f"ev{i}", None)
        assert clock.prune(lambda e: False) == 17
        assert clock.pending() == 0
        # The clock stays usable: schedule after a full prune works and
        # deadlines are still relative to the unchanged `now`.
        clock.schedule(0.5, "fresh", None)
        event, _ = clock.fire_next()
        assert event == "fresh" and clock.now == 0.5

    def test_fire_never_moves_time_backwards(self):
        clock = VirtualClock()
        clock.schedule(1.0, "late", None)
        clock.now = 5.0  # delivery pacing overtook the deadline
        event, _ = clock.fire_next()
        assert event == "late" and clock.now == 5.0
