"""Pippenger MSM kernel: differential parity against the host curve
reference, plus the cofactored-vs-strict adversarial boundary.

The MSM is the reduction engine behind the RLC batch-verify fast path
(rlc_kernel drives two of them); these tests pin it to the serial host
arithmetic on random inputs and document the ONE divergence class the
batch equation is allowed to have: crafted small-order/torsion
signatures, where batch-accept means cofactored-valid (PARITY.md).
"""

import numpy as np
import pytest

from hyperdrive_tpu.crypto import ed25519 as hed
from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier, _recode_signed
from hyperdrive_tpu.ops.msm import msm_kernel, msm_plan, plan_groups


def _host_affine(p):
    # Host curve ops are extended homogeneous (X, Y, Z, T) with Z != 1;
    # the kernel takes z = 1 affine limbs, so normalize first.
    x, y, z, _ = p
    zinv = pow(z, hed.P - 2, hed.P)
    return (x * zinv) % hed.P, (y * zinv) % hed.P


def _ext(p):
    x, y = p
    return (x, y, 1, x * y % hed.P)


def _host_msm(points, scalars):
    acc = hed.IDENTITY
    for p, s in zip(points, scalars):
        acc = hed.point_add(acc, hed.scalar_mult(s, _ext(p)))
    return _host_affine(acc)


def _pack_points(points):
    px = np.stack([fe.to_limbs(p[0]) for p in points])
    py = np.stack([fe.to_limbs(p[1]) for p in points])
    pt = np.stack([fe.to_limbs(p[0] * p[1] % hed.P) for p in points])
    return px, py, pt


def _digits(scalars, windows):
    # One extra zero nibble absorbs the signed-recode carry out of the
    # top window (rlc_kernel runs 33 windows for 128-bit z the same way).
    nibs = np.array(
        [
            [(s >> (4 * w)) & 0xF for w in range(windows + 1)]
            for s in scalars
        ],
        dtype=np.int32,
    )
    return np.asarray(_recode_signed(nibs))


def _affine(ext):
    sx, sy, sz, _ = ext
    zi = pow(int(fe.from_limbs(np.asarray(sz))[0]), hed.P - 2, hed.P)
    return (
        int(fe.from_limbs(np.asarray(sx))[0]) * zi % hed.P,
        int(fe.from_limbs(np.asarray(sy))[0]) * zi % hed.P,
    )


def test_plan_groups_geometry():
    # Power-of-two group counts, ceil-division serial depth, all lanes
    # covered, and the small-batch floor.
    for n in (1, 7, 8, 64, 256, 1024, 16384, 65536):
        G, g = plan_groups(n)
        assert G * g >= n
        assert G == 1 or (G & (G - 1)) == 0
        assert msm_plan(n, 64)["reduction_depth"] >= 7
    assert plan_groups(65536) == (1024, 64)
    assert plan_groups(7) == (1, 7)


@pytest.mark.slow  # the CI msm-parity smoke runs this exact differential
def test_msm_matches_host_reference(rng):
    # Same shape as the CI smoke (python -m hyperdrive_tpu.ops
    # msm-parity) so the persistent compile cache is shared: one XLA
    # compile covers both.
    n, windows = 37, 16
    points, scalars = [], []
    for _ in range(n):
        points.append(
            _host_affine(hed.scalar_mult(rng.randrange(1, hed.L), hed.BASE))
        )
        scalars.append(rng.randrange(0, 1 << (4 * windows)))
    # Exercise the trash slot: zero scalars and duplicate points.
    scalars[3] = 0
    points[11] = points[4]

    px, py, pt = _pack_points(points)
    got = _affine(msm_kernel(px, py, pt, _digits(scalars, windows)))
    assert got == _host_msm(points, scalars)


# ------------------------------------------------- cofactored semantics


def _order8_point():
    """An order-8 torsion point (the canonical small-order vector of the
    "Taming the many EdDSAs" test suite)."""
    for seed in range(2, 50):
        p = hed.point_decompress(bytes([seed]) + bytes(31))
        if p is None:
            continue
        q = hed.scalar_mult(hed.L, p)
        o, acc = 1, q
        while not hed.point_equal(acc, hed.IDENTITY) and o <= 8:
            acc = hed.point_add(acc, q)
            o += 1
        if o == 8:
            return q
    raise AssertionError("no order-8 point found")


def small_order_item():
    """(pub, msg, sig) that is cofactored-valid but strict-invalid:
    A = R = an 8-torsion point, s = 0. Then [8]([s]B - R - [k]A) is the
    identity (the cofactor kills the torsion), while [s]B == R + [k]A
    itself fails for a suitably chosen message."""
    t8 = _order8_point()
    enc = hed.point_compress(t8)
    sig = enc + bytes(32)
    for i in range(64):
        msg = b"small-order-%d" % i
        k = hed.challenge_scalar(enc, enc, msg)
        rka = hed.point_add(t8, hed.scalar_mult(k, t8))
        if not hed.point_equal(hed.IDENTITY, rka):
            return enc, msg, sig
    raise AssertionError("no diverging message found")


def test_small_order_vector_documents_cofactored_divergence(ring):
    # The PARITY.md divergence class, pinned: the RLC batch equation is
    # cofactored (3 final doublings), the per-signature ladder and the
    # host reference are strict — a crafted torsion signature is the
    # only input family where they may disagree, and callers needing
    # strict semantics keep rlc=False for exactly this reason.
    pub, msg, sig = small_order_item()
    assert not hed.verify(pub, msg, sig)  # strict host: reject

    item = (pub, msg, sig)
    good = []
    for i in range(3):
        kp = ring[i]
        m = bytes([i]) * 24
        good.append((kp.public, m, hed.sign(kp.seed, m)))

    ladder = TpuBatchVerifier(buckets=(16,), rlc=False)
    strict = ladder.verify_signatures(good + [item]).tolist()
    assert strict == [True, True, True, False]

    rlc = TpuBatchVerifier(buckets=(16,), rlc=True)
    batched = rlc.verify_signatures(good + [item]).tolist()
    # The combined cofactored equation absorbs the torsion: the batch
    # accepts all four lanes in ONE launch, no fallback fired.
    assert batched == [True, True, True, True]
    assert rlc.rlc_fallbacks == 0


@pytest.mark.slow
def test_msm_torsion_points_in_batch_match_reference(rng):
    # Mixed-cofactor MSM input: torsion points alongside prime-order
    # ones must still reduce to the host reference sum exactly — the
    # kernel is plain group arithmetic; cofactor semantics only enter at
    # the rlc_kernel's final check.
    t8 = _host_affine(_order8_point())
    n, windows = 37, 16
    points = [t8 if i % 5 == 0
              else _host_affine(hed.scalar_mult(i + 1, hed.BASE))
              for i in range(n)]
    scalars = [rng.randrange(0, 1 << (4 * windows)) for _ in range(n)]
    px, py, pt = _pack_points(points)
    got = _affine(msm_kernel(px, py, pt, _digits(scalars, windows)))
    assert got == _host_msm(points, scalars)


@pytest.fixture(scope="module")
def ring():
    from hyperdrive_tpu.crypto.keys import KeyRing

    return KeyRing.deterministic(4, namespace=b"msmtest")
