"""Quorum tally kernels: differential against a naive Python count."""

import numpy as np

import jax
import jax.numpy as jnp

from hyperdrive_tpu.ops import tally


def test_pack_value_roundtrip(rng):
    v = rng.randbytes(32)
    words = tally.pack_value(v)
    assert words.shape == (8,)
    back = b"".join(
        int(np.uint32(w)).to_bytes(4, "little") for w in words
    )
    assert back == v


def test_counts_match_naive(rng):
    R, V = 6, 32
    f = 10
    values = [rng.randbytes(32) for _ in range(4)] + [b"\x00" * 32]
    votes = [[values[rng.randrange(len(values))] for _ in range(V)] for _ in range(R)]
    present = [[rng.random() < 0.8 for _ in range(V)] for _ in range(R)]
    targets = [values[rng.randrange(len(values) - 1)] for _ in range(R)]

    vote_t = jnp.asarray(
        np.stack([tally.pack_values(row) for row in votes])
    )
    present_t = jnp.asarray(np.array(present))
    target_t = jnp.asarray(tally.pack_values(targets))

    counts = jax.jit(tally.tally_counts)(vote_t, present_t, target_t)

    for r in range(R):
        want_match = sum(
            1 for v, p in zip(votes[r], present[r]) if p and v == targets[r]
        )
        want_nil = sum(
            1 for v, p in zip(votes[r], present[r]) if p and v == b"\x00" * 32
        )
        want_total = sum(1 for p in present[r] if p)
        assert int(counts["matching"][r]) == want_match
        assert int(counts["nil"][r]) == want_nil
        assert int(counts["total"][r]) == want_total

    flags = tally.quorum_flags(counts, jnp.int32(f))
    for r in range(R):
        assert bool(flags["quorum_matching"][r]) == (
            int(counts["matching"][r]) >= 2 * f + 1
        )
        assert bool(flags["skip_eligible"][r]) == (int(counts["total"][r]) >= f + 1)


def test_absent_votes_never_count():
    R, V = 1, 8
    target = b"\x07" * 32
    vote_t = jnp.asarray(
        np.stack([tally.pack_values([target] * V)])
    )
    present_t = jnp.zeros((R, V), dtype=bool)
    target_t = jnp.asarray(tally.pack_values([target]))
    counts = tally.tally_counts(vote_t, present_t, target_t)
    assert int(counts["matching"][0]) == 0
    assert int(counts["total"][0]) == 0
