"""Timers: scaling math, real-clock firing, nil-handler safety.

Mirrors timer/timer_test.go (scaled down: millisecond timeouts).
"""

import random
import threading
import time

from hyperdrive_tpu.messages import Timeout
from hyperdrive_tpu.timer import LinearTimer, VirtualTimer
from hyperdrive_tpu.types import MessageType


def test_duration_scaling():
    t = LinearTimer(timeout=2.0, timeout_scaling=0.5)
    assert t.duration_at(1, 0) == 2.0
    assert t.duration_at(1, 1) == 3.0
    assert t.duration_at(1, 4) == 6.0
    t2 = LinearTimer(timeout=2.0, timeout_scaling=0.0)
    assert t2.duration_at(1, 10) == 2.0


def test_fires_correct_handler_within_window():
    fired = []
    done = threading.Event()

    def on_prevote(t: Timeout):
        fired.append(t)
        done.set()

    timer = LinearTimer(
        handle_timeout_prevote=on_prevote,
        timeout=0.02,
        timeout_scaling=0.5,
    )
    start = time.monotonic()
    timer.timeout_prevote(3, 1)
    assert done.wait(2.0), "timeout handler never fired"
    elapsed = time.monotonic() - start
    assert elapsed >= 0.02  # not early
    assert fired == [Timeout(MessageType.PREVOTE, 3, 1)]


def test_other_handlers_not_invoked():
    fired = {"propose": 0, "precommit": 0}
    done = threading.Event()
    timer = LinearTimer(
        handle_timeout_propose=lambda t: fired.__setitem__("propose", 1),
        handle_timeout_precommit=lambda t: (
            fired.__setitem__("precommit", 1),
            done.set(),
        ),
        timeout=0.01,
    )
    timer.timeout_precommit(1, 0)
    assert done.wait(2.0)
    assert fired == {"propose": 0, "precommit": 1}


def test_nil_handler_is_safe():
    timer = LinearTimer(timeout=0.001)
    timer.timeout_propose(1, 0)
    timer.timeout_prevote(1, 0)
    timer.timeout_precommit(1, 0)
    time.sleep(0.01)  # nothing to assert — must simply not raise


class TestDurationScaling:
    # Reference: timer_test.go:289+ — the linear scaling law over ranges.
    def test_scaling_math_over_rounds(self):
        t = LinearTimer(timeout=2.0, timeout_scaling=0.5)
        assert t.duration_at(1, 0) == 2.0
        assert t.duration_at(1, 1) == 3.0
        assert t.duration_at(99, 4) == 6.0  # height never matters
        for r in range(32):
            assert t.duration_at(7, r) == 2.0 * (1 + 0.5 * r)

    def test_zero_scaling_is_constant(self):
        t = LinearTimer(timeout=5.0, timeout_scaling=0.0)
        assert all(t.duration_at(1, r) == 5.0 for r in range(10))

    def test_virtual_matches_linear_law(self):
        class FakeClock:
            def __init__(self):
                self.scheduled = []

            def schedule(self, delay, event, handler):
                self.scheduled.append((delay, event))

        clock = FakeClock()
        vt = VirtualTimer(clock, timeout=1.0, timeout_scaling=0.25)
        vt.timeout_propose(3, 4)
        vt.timeout_precommit(3, 0)
        (d1, e1), (d2, e2) = clock.scheduled
        assert d1 == 2.0 and e1.round == 4
        assert d2 == 1.0 and e2.message_type == MessageType.PRECOMMIT


class TestRealClockFiring:
    # Reference: timer_test.go:95-288 — real-sleep firing windows, typed
    # channels, nil-handler safety. Tolerances are generous (CI machines).
    def test_fires_only_the_scheduled_type(self):
        fired = {"propose": [], "prevote": [], "precommit": []}
        t = LinearTimer(
            handle_timeout_propose=lambda ev: fired["propose"].append(ev),
            handle_timeout_prevote=lambda ev: fired["prevote"].append(ev),
            handle_timeout_precommit=lambda ev: fired["precommit"].append(ev),
            timeout=0.02,
            timeout_scaling=0.5,
        )
        t.timeout_prevote(5, 2)
        time.sleep(0.15)
        assert fired["propose"] == [] and fired["precommit"] == []
        assert [ (e.height, e.round, e.message_type) for e in fired["prevote"] ] == [
            (5, 2, MessageType.PREVOTE)
        ]

    def test_does_not_fire_early(self):
        fired = []
        t = LinearTimer(
            handle_timeout_propose=fired.append, timeout=0.8, timeout_scaling=0.0
        )
        t.timeout_propose(1, 0)
        time.sleep(0.05)
        # 0.75s of slack before the deadline: a descheduling hiccup on a
        # loaded CI machine must not flake this.
        assert fired == []
        time.sleep(1.0)
        assert len(fired) == 1

    def test_concurrent_timeouts_all_fire(self):
        fired = []
        t = LinearTimer(
            handle_timeout_precommit=fired.append, timeout=0.02, timeout_scaling=0.0
        )
        for r in range(8):
            t.timeout_precommit(1, r)
        time.sleep(0.3)
        assert sorted(e.round for e in fired) == list(range(8))


class TestTimeoutShaping:
    # The optional max-cap and jitter shapers (ISSUE 5 satellite). Both
    # default OFF: the bare linear law must be bit-identical to before.

    def test_defaults_reproduce_linear_law_exactly(self):
        t = LinearTimer(timeout=2.0, timeout_scaling=0.5)
        assert t.max_timeout is None and t.jitter == 0.0
        for r in range(64):
            assert t.duration_at(1, r) == 2.0 * (1 + 0.5 * r)

    def test_max_timeout_caps_linear_growth(self):
        t = LinearTimer(timeout=2.0, timeout_scaling=0.5, max_timeout=5.0)
        # d = 2 + r: rounds 0..3 are under the cap and untouched...
        assert t.duration_at(1, 0) == 2.0
        assert t.duration_at(1, 3) == 5.0  # == cap, NOT capped
        # ...every later round clamps to the cap instead of growing.
        for r in range(4, 40):
            assert t.duration_at(1, r) == 5.0

    def test_jitter_stays_in_band(self):
        rng = random.Random(99)
        t = LinearTimer(
            timeout=2.0, timeout_scaling=0.5, jitter=0.25, rng=rng
        )
        for r in range(50):
            base = 2.0 + r
            d = t.duration_at(1, r)
            assert base <= d < base * 1.25

    def test_seeded_jitter_is_deterministic(self):
        mk = lambda: LinearTimer(
            timeout=1.0,
            timeout_scaling=0.5,
            jitter=0.3,
            rng=random.Random(4242),
        )
        a, b = mk(), mk()
        seq_a = [a.duration_at(1, r) for r in range(20)]
        seq_b = [b.duration_at(1, r) for r in range(20)]
        assert seq_a == seq_b
        # And jitter actually varies the durations (not a constant offset).
        assert len({round(d - (1.0 + 0.5 * r), 9)
                    for r, d in enumerate(seq_a)}) > 1

    def test_cap_applies_before_jitter(self):
        # A near-1.0 draw on a capped round must land in
        # [cap, cap*(1+jitter)), not [uncapped, uncapped*(1+jitter)).
        class TopRng:
            def random(self):
                return 0.999

        t = LinearTimer(
            timeout=2.0,
            timeout_scaling=0.5,
            max_timeout=5.0,
            jitter=0.2,
            rng=TopRng(),
        )
        d = t.duration_at(1, 20)  # uncapped law would give 12.0
        assert 5.0 <= d < 6.0

    def test_virtual_timer_honors_cap_and_jitter(self):
        class FakeClock:
            def __init__(self):
                self.scheduled = []

            def schedule(self, delay, event, handler):
                self.scheduled.append((delay, event))

        clock = FakeClock()
        vt = VirtualTimer(
            clock,
            timeout=1.0,
            timeout_scaling=1.0,
            max_timeout=3.0,
            jitter=0.5,
            rng=random.Random(7),
        )
        vt.timeout_propose(1, 9)  # uncapped law: 10.0 -> capped 3.0
        vt.timeout_prevote(1, 0)  # base 1.0, under the cap
        (d1, e1), (d2, e2) = clock.scheduled
        assert 3.0 <= d1 < 4.5 and e1.message_type == MessageType.PROPOSE
        assert 1.0 <= d2 < 1.5 and e2.message_type == MessageType.PREVOTE
