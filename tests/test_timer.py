"""Timers: scaling math, real-clock firing, nil-handler safety.

Mirrors timer/timer_test.go (scaled down: millisecond timeouts).
"""

import threading
import time

from hyperdrive_tpu.messages import Timeout
from hyperdrive_tpu.timer import LinearTimer
from hyperdrive_tpu.types import MessageType


def test_duration_scaling():
    t = LinearTimer(timeout=2.0, timeout_scaling=0.5)
    assert t.duration_at(1, 0) == 2.0
    assert t.duration_at(1, 1) == 3.0
    assert t.duration_at(1, 4) == 6.0
    t2 = LinearTimer(timeout=2.0, timeout_scaling=0.0)
    assert t2.duration_at(1, 10) == 2.0


def test_fires_correct_handler_within_window():
    fired = []
    done = threading.Event()

    def on_prevote(t: Timeout):
        fired.append(t)
        done.set()

    timer = LinearTimer(
        handle_timeout_prevote=on_prevote,
        timeout=0.02,
        timeout_scaling=0.5,
    )
    start = time.monotonic()
    timer.timeout_prevote(3, 1)
    assert done.wait(2.0), "timeout handler never fired"
    elapsed = time.monotonic() - start
    assert elapsed >= 0.02  # not early
    assert fired == [Timeout(MessageType.PREVOTE, 3, 1)]


def test_other_handlers_not_invoked():
    fired = {"propose": 0, "precommit": 0}
    done = threading.Event()
    timer = LinearTimer(
        handle_timeout_propose=lambda t: fired.__setitem__("propose", 1),
        handle_timeout_precommit=lambda t: (
            fired.__setitem__("precommit", 1),
            done.set(),
        ),
        timeout=0.01,
    )
    timer.timeout_precommit(1, 0)
    assert done.wait(2.0)
    assert fired == {"propose": 0, "precommit": 1}


def test_nil_handler_is_safe():
    timer = LinearTimer(timeout=0.001)
    timer.timeout_propose(1, 0)
    timer.timeout_prevote(1, 0)
    timer.timeout_precommit(1, 0)
    time.sleep(0.01)  # nothing to assert — must simply not raise
