"""Property specs for utils/trace.py: Histogram window + null overhead.

hypothesis is not a local dependency (see test_columnar_parity.py), so
the properties run as seeded random loops — replayable via
HYPERDRIVE_TEST_SEED, wide enough to cross every bucket boundary and
wrap the sample ring several times.
"""

import random

from hyperdrive_tpu.obs.recorder import (
    NULL_BOUND,
    NULL_RECORDER,
    NullBound,
    NullRecorder,
)
from hyperdrive_tpu.utils.trace import NULL_TRACER, Histogram, NullTracer, Tracer


def _random_values(rng, n):
    # Log-uniform over the bucket range plus exact boundary hits: the
    # bucket-placement property is only interesting at the edges.
    out = []
    for _ in range(n):
        if rng.random() < 0.2:
            out.append(rng.choice(Histogram.DEFAULT_BUCKETS))
        else:
            out.append(10.0 ** rng.uniform(-7, 3.5))
    return out


# ---------------------------------------------------------------- ring window


def test_ring_window_is_exactly_the_most_recent_max_samples(rng):
    for trial in range(20):
        m = rng.randint(1, 64)
        n = rng.randint(m + 1, 6 * m)  # always wraps at least once
        h = Histogram(max_samples=m)
        values = _random_values(rng, n)
        for v in values:
            h.observe(v)
        # The retained sample multiset is the last m observations — the
        # off-by-one this spec pins down kept the oldest sample alive
        # for a full extra lap.
        assert sorted(h._samples) == sorted(values[-m:]), (
            f"trial {trial}: ring window drifted (m={m}, n={n})"
        )
        assert h.quantile(0.0) == min(values[-m:])
        assert h.quantile(1.0) == max(values[-m:])


def test_ring_window_below_capacity_keeps_everything(rng):
    h = Histogram(max_samples=128)
    values = _random_values(rng, 100)
    for v in values:
        h.observe(v)
    assert sorted(h._samples) == sorted(values)


# ------------------------------------------------------------------ quantiles


def test_quantiles_are_monotone_and_within_sample_range(rng):
    for _ in range(10):
        h = Histogram(max_samples=256)
        values = _random_values(rng, rng.randint(1, 400))
        for v in values:
            h.observe(v)
        qs = sorted(rng.uniform(0.0, 1.0) for _ in range(9))
        quants = [h.quantile(q) for q in qs]
        assert quants == sorted(quants), "quantile must be monotone in q"
        lo, hi = min(h._samples), max(h._samples)
        assert all(lo <= x <= hi for x in quants)


def test_quantile_of_empty_histogram_is_zero():
    h = Histogram()
    assert h.quantile(0.5) == 0.0
    assert h.mean == 0.0


# ----------------------------------------------------------- bucket invariants


def test_bucket_counts_partition_total_and_sum_tracks_all(rng):
    h = Histogram(max_samples=32)  # much smaller than n: ring can't help
    values = _random_values(rng, 500)
    for v in values:
        h.observe(v)
    # Bucket counts never drop, even though the raw-sample ring does:
    # they partition the full observation count.
    assert sum(h.counts) == h.total == len(values)
    assert abs(h.sum - sum(values)) < 1e-6 * max(1.0, abs(sum(values)))
    assert abs(h.mean - sum(values) / len(values)) < 1e-9 * h.mean


def test_bucket_placement_is_bisect_left_on_boundaries():
    h = Histogram(buckets=(1.0, 10.0), max_samples=8)
    for v in (0.5, 1.0, 5.0, 10.0, 50.0):
        h.observe(v)
    # bisect_left: a value equal to a boundary lands in that boundary's
    # bucket, not the next one up.
    assert h.counts == [2, 2, 1]


# -------------------------------------------------------------- null overhead


def test_null_tracer_records_nothing():
    assert isinstance(NULL_TRACER, NullTracer)
    NULL_TRACER.count("x.y", 5)
    NULL_TRACER.observe("x.y", 1.0)
    with NULL_TRACER.span("x.y"):
        pass
    snap = NULL_TRACER.snapshot()
    assert snap == {"counters": {}, "histograms": {}}


def test_null_recorder_and_bound_are_inert_and_shared():
    assert isinstance(NULL_RECORDER, NullRecorder)
    assert isinstance(NULL_BOUND, NullBound)
    # Every scoped() handle off the null recorder is the one shared
    # singleton — the identity the hot-path guards key on.
    assert NULL_RECORDER.scoped(0) is NULL_BOUND
    assert NULL_RECORDER.scoped(7) is NULL_BOUND
    NULL_BOUND.emit("commit", 1, 0)
    NULL_RECORDER.emit("commit", 0, 1, 0)
    assert len(NULL_RECORDER) == 0
    assert NULL_RECORDER.dropped == 0


def test_disabled_instrumentation_overhead_smoke():
    """200k no-op emits/counts complete in interactive time.

    Not a benchmark — a regression tripwire for someone adding real work
    to the null objects. The generous bound absorbs CI-host noise; the
    measured per-call figures live in OBSERVABILITY.md.
    """
    import time

    t0 = time.perf_counter()
    for _ in range(200_000):
        NULL_BOUND.emit("commit", 1, 0)
        NULL_TRACER.count("replica.msg.prevote")
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, f"null instrumentation took {elapsed:.2f}s"


def test_live_tracer_snapshot_matches_observations(rng):
    tr = Tracer(time_fn=None, threadsafe=rng.random() < 0.5)
    tr.count("a.b", 3)
    tr.count("a.b")
    for v in (0.1, 0.2, 0.3):
        tr.observe("lat.s", v)
    snap = tr.snapshot()
    assert snap["counters"]["a.b"] == 4
    assert snap["histograms"]["lat.s"]["count"] == 3
    assert abs(snap["histograms"]["lat.s"]["mean"] - 0.2) < 1e-12


# ------------------------------------------- device track + flow arrows


_PIPELINED = dict(
    n=4, target_height=6, seed=7, sign=True, burst=True, observe=True,
    pipeline_heights=True,
)


def _pipelined_sim():
    # Jax-free: sign=True defaults the batch verifier to HostVerifier.
    from hyperdrive_tpu.harness.sim import Simulation

    sim = Simulation(**_PIPELINED)
    assert sim.run().completed
    return sim


def test_device_track_slices_carry_launch_args_and_name():
    import json

    from hyperdrive_tpu.obs.perfetto import DEVICE_TID, to_trace_events

    sim = _pipelined_sim()
    trace = to_trace_events(sim.obs.snapshot())
    launches = [
        e for e in trace
        if e.get("tid") == DEVICE_TID and e["ph"] == "X"
    ]
    assert launches, "pipelined observed run must render device slices"
    for e in launches:
        args = e["args"]
        assert {"launch_id", "rows", "lanes", "occupancy",
                "queue_wait", "commands"} <= set(args)
        assert e["dur"] >= 1.0
    # The device track is named in the metadata.
    names = {
        m["tid"]: m["args"]["name"]
        for m in trace
        if m["ph"] == "M" and m["name"] == "thread_name"
    }
    assert names[DEVICE_TID] == "device"
    json.dumps(trace)  # schema stays JSON-serializable end to end


def test_every_gated_commit_links_to_exactly_one_launch():
    from hyperdrive_tpu.obs.perfetto import DEVICE_TID, to_trace_events

    sim = _pipelined_sim()
    events = sim.obs.snapshot()
    commits = [e for e in events if e.kind == "sched.launch.commit"]
    assert commits, "pipelined run must gate commits behind launches"
    launch_ids = {
        e.detail for e in events if e.kind == "sched.launch.end"
    }
    for c in commits:
        assert c.detail in launch_ids  # exactly one covering launch

    trace = to_trace_events(events)
    # Flow-arrow pairing: within each category every id appears exactly
    # once as a start and once as a finish — one unbroken arrow per
    # command (cmdflow) and per gated commit (commitflow).
    starts = sorted(
        (e["cat"], e["id"]) for e in trace if e["ph"] == "s"
    )
    finishes = sorted(
        (e["cat"], e["id"]) for e in trace if e["ph"] == "f"
    )
    assert starts == finishes
    assert len(starts) == len(set(starts))
    n_commit_flows = sum(
        1 for c, _ in starts if c == "commitflow"
    )
    assert n_commit_flows == len(commits)
    # Commit-flow starts anchor on the device track, finishes on the
    # committing replica's track.
    for e in trace:
        if e["ph"] == "s" and e["cat"] == "commitflow":
            assert e["tid"] == DEVICE_TID
        if e["ph"] == "f" and e["cat"] == "commitflow":
            assert e["tid"] >= 0


def test_fixed_seed_runs_are_digest_identical_journal_registry_trace():
    import json

    from hyperdrive_tpu.obs.perfetto import to_trace_events

    a, b = _pipelined_sim(), _pipelined_sim()
    assert a.obs.digest() == b.obs.digest()
    a.metrics_snapshot()
    b.metrics_snapshot()
    assert a.registry.digest() == b.registry.digest()
    trace_a = json.dumps(to_trace_events(a.obs.snapshot()), sort_keys=True)
    trace_b = json.dumps(to_trace_events(b.obs.snapshot()), sort_keys=True)
    assert trace_a == trace_b
