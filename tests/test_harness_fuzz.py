"""Randomized whole-network scenario fuzz.

One hypothesis property over the full Simulation parameter space: any
combination of network size, delivery mode (lock-step / burst / batched
ingestion / device vote-grid tallies), adversarial reorder, offline
replicas, and signing must complete to the target height with
byte-identical commit chains, and replay from its own record exactly;
a below-quorum example class must stall without ever violating safety.
This is the generalized form of the reference's hand-picked scenario
list (replica_test.go:372-847): instead of six fixed scenarios, every
example IS a scenario, and a failing one shrinks to a minimal
reproduction.
"""

import pytest

pytest.importorskip(
    "hypothesis",
    reason="property-based specs need hypothesis (not in this image)",
)

from hypothesis import given, settings, strategies as st

from hyperdrive_tpu.harness import Simulation

SCENARIOS = settings(max_examples=20, deadline=None)


@st.composite
def scenario(draw):
    n = draw(st.integers(min_value=4, max_value=13))
    f = n // 3
    # Keep at least 2f+1 online so completion is expected; a separate
    # example class drops below quorum and expects a stall.
    max_offline = max(n - (2 * f + 1), 0)
    n_offline = draw(st.integers(min_value=0, max_value=max_offline))
    offline = set(range(n - n_offline, n))
    burst = draw(st.booleans())
    # The mode knobs only exist under burst; drawing them unconditionally
    # would burn examples on duplicate scenarios.
    batch_ingest = draw(st.booleans()) if burst else None
    device_tally = (
        draw(st.booleans()) if burst and batch_ingest else False
    )
    return dict(
        n=n,
        seed=draw(st.integers(min_value=0, max_value=2**31)),
        target_height=draw(st.integers(min_value=2, max_value=6)),
        burst=burst,
        batch_ingest=batch_ingest,
        device_tally=device_tally,
        reorder=draw(st.booleans()),
        offline=offline,
        sign=draw(st.booleans()),
    )


@SCENARIOS
@given(params=scenario())
def test_any_scenario_is_safe_and_replays(params):
    sim = Simulation(**params)
    res = sim.run(max_steps=400_000)
    # Liveness: with >= 2f+1 online the network must reach the target.
    # (Timeout rounds via offline proposers are expected and fine.)
    assert res.completed, (
        f"stalled at {res.heights} with {len(params['offline'])} offline "
        f"of n={params['n']}"
    )
    # Safety: identical commit chains on every live replica, always.
    res.assert_safety()
    # Determinism: the record replays to the same commits.
    replayed = Simulation.replay(
        res.record, sign=params["sign"], offline=params["offline"]
    )
    assert replayed.commits == res.commits
    assert replayed.heights == res.heights


@SCENARIOS
@given(
    n=st.integers(min_value=4, max_value=10),
    seed=st.integers(min_value=0, max_value=2**31),
    burst=st.booleans(),
)
def test_below_quorum_always_stalls_and_stays_safe(n, seed, burst):
    f = n // 3
    offline = set(range(2 * f, n))  # exactly 2f online: one short of quorum
    sim = Simulation(
        n=n, seed=seed, target_height=3, burst=burst, offline=offline
    )
    res = sim.run(max_steps=60_000)
    assert not res.completed  # liveness requires 2f+1
    res.assert_safety()  # but safety never breaks
    assert all(h == 1 for i, h in enumerate(res.heights) if i not in offline)
