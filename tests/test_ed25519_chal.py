"""Device-side challenge derivation (ops/sha512_jax.py + the chalwire
verify path): differential against hashlib, Python bignum mod L, the host
oracle, and the host-hashed semiwire path.

The security-relevant property: the challenge k derived ON DEVICE is the
CANONICAL SHA-512(R||A||M) mod L — bit-identical to the host packer's —
so moving the hash across the host/device boundary cannot change a single
verdict. Reference trust-model seam: the reference assumes authenticated
messages (/root/reference/process/process.go:95-98); this framework makes
verification explicit and must keep every path in exact agreement.
"""

import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.ops.sha512_jax import (
    L,
    bytes_from_limbs13,
    challenge_scalar_device,
    limbs13_from_bytes,
    sc_reduce_limbs,
    sha512_cat,
)
from hyperdrive_tpu.ops.ed25519_wire import (
    Ed25519WireHost,
    ValidatorTable,
    make_chalwire_verify_fn,
    make_semiwire_verify_fn,
)

RNG = np.random.default_rng(0xC11A)


def _rows(n, w=32):
    return RNG.integers(0, 256, (n, w), dtype=np.uint8)


# ----------------------------------------------------------------- SHA-512


def test_sha512_matches_hashlib_on_96_byte_preimages():
    r, a, m = _rows(64), _rows(64), _rows(64)
    got = np.asarray(sha512_cat((jnp.asarray(r), jnp.asarray(a),
                                 jnp.asarray(m))))
    for i in range(64):
        want = hashlib.sha512(bytes(r[i]) + bytes(a[i]) + bytes(m[i]))
        assert bytes(got[i]) == want.digest()


@pytest.mark.parametrize("width", [0, 1, 32, 55, 96, 111])
def test_sha512_single_block_widths(width):
    """Every padding layout a single block admits, incl. the empty
    message and the 111-byte maximum (112 would need a second block)."""
    data = _rows(8, width) if width else np.zeros((8, 0), dtype=np.uint8)
    got = np.asarray(sha512_cat((jnp.asarray(data),)))
    for i in range(8):
        assert bytes(got[i]) == hashlib.sha512(bytes(data[i])).digest()


def test_sha512_rejects_multi_block_widths():
    with pytest.raises(ValueError):
        sha512_cat((jnp.zeros((2, 112), dtype=jnp.uint8),))


def test_sha512_fixed_vector():
    """One pinned vector so a wrong constant table cannot hide behind a
    differential that uses the same wrong table on both sides (hashlib
    is independent, but pin one literal anyway)."""
    got = np.asarray(sha512_cat((jnp.frombuffer(b"abc", dtype=np.uint8)
                                 .reshape(1, 3),)))
    assert bytes(got[0]).hex().startswith("ddaf35a193617aba")


# ------------------------------------------------------------- mod-L limbs


def _reduce_bytes(h64: np.ndarray) -> np.ndarray:
    limbs = limbs13_from_bytes(jnp.asarray(h64), 40)
    return np.asarray(bytes_from_limbs13(sc_reduce_limbs(limbs)))


def test_sc_reduce_random_differential():
    h = _rows(128, 64)
    k = _reduce_bytes(h)
    for i in range(len(h)):
        want = int.from_bytes(bytes(h[i]), "little") % L
        assert int.from_bytes(bytes(k[i]), "little") == want


def test_sc_reduce_edge_values():
    """Canonicity boundaries: 0, L itself and its neighbours/multiples,
    the 2^252 fold pivot, the all-ones maximum, and exact multiples of L
    near the top of the 512-bit range (the conditional-subtract path)."""
    top = ((1 << 512) - 1) // L
    vals = [0, 1, L - 1, L, L + 1, 2 * L, 2 * L - 1, 4 * L + 3,
            (1 << 252) - 1, 1 << 252, (1 << 252) + 1, (1 << 512) - 1,
            top * L, top * L - 1, (1 << 260) - 1, 1 << 384]
    h = np.stack([
        np.frombuffer(v.to_bytes(64, "little"), dtype=np.uint8)
        for v in vals
    ])
    k = _reduce_bytes(h)
    for i, v in enumerate(vals):
        got = int.from_bytes(bytes(k[i]), "little")
        assert got == v % L, f"case {i}: {hex(v)}"
        assert got < L


def test_limb_byte_round_trip():
    rows = _rows(32)
    limbs = limbs13_from_bytes(jnp.asarray(rows), 20)
    # 20 limbs cover 260 bits; a 32-byte value < 2^256 round-trips.
    back = np.asarray(bytes_from_limbs13(limbs, 32))
    np.testing.assert_array_equal(back, rows)


# ------------------------------------------------------ challenge scalars


def test_challenge_scalar_device_matches_host_oracle():
    r, a, m = _rows(32), _rows(32), _rows(32)
    got = np.asarray(challenge_scalar_device(
        jnp.asarray(r), jnp.asarray(a), jnp.asarray(m)))
    for i in range(32):
        want = host_ed.challenge_scalar(bytes(r[i]), bytes(a[i]),
                                        bytes(m[i]))
        assert bytes(got[i]) == want.to_bytes(32, "little")


# -------------------------------------------------------- chalwire verify


@pytest.fixture(scope="module")
def ring_table():
    ring = KeyRing.deterministic(8, namespace=b"chalwire")
    table = ValidatorTable([ring[v].public for v in range(8)])
    return ring, table


def _signed_items(ring, n, seed=7):
    rng = np.random.default_rng(seed)
    items = []
    for i in range(n):
        v = i % 8
        digest = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        items.append((ring[v].public, digest, ring[v].sign_digest(digest)))
    return items


def _chal_verify(host, table, items):
    (idx, r, s, m), prevalid, n = host.pack_wire_challenge(items, table)
    fn = make_chalwire_verify_fn()
    ok = np.asarray(fn(jnp.asarray(idx), jnp.asarray(r), jnp.asarray(s),
                       jnp.asarray(m), *table.arrays_chal()))
    return (ok & prevalid)[:n]


def test_chalwire_accepts_valid_rejects_tampered(ring_table):
    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    items = _signed_items(ring, 24)
    # Tamper: flipped s, wrong digest, truncated sig, swapped sender
    # (valid signature attributed to the wrong table entry), non-canonical
    # R (y >= p), s >= L (malleability).
    items[1] = (items[1][0], items[1][1],
                items[1][2][:63] + bytes([items[1][2][63] ^ 1]))
    items[2] = (items[2][0], bytes(32), items[2][2])
    items[3] = (items[3][0], items[3][1], b"short")
    items[4] = (ring[5].public, items[4][1], items[4][2])
    items[5] = (items[5][0], items[5][1],
                (host_ed.P).to_bytes(32, "little") + items[5][2][32:])
    items[6] = (items[6][0], items[6][1],
                items[6][2][:32] + L.to_bytes(32, "little"))
    ok = _chal_verify(host, table, items)
    want = np.array([
        len(sig) == 64 and host_ed.verify(pub, d, sig)
        for pub, d, sig in items
    ])
    np.testing.assert_array_equal(ok, want)
    assert not want[1:7].any() and want[0] and want[7:].all()


def test_chalwire_matches_semiwire_bit_for_bit(ring_table):
    """The device-derived k is canonical, so the chal path and the
    host-hashed semiwire path must agree on every lane — including
    garbage lanes whose 'signatures' are random bytes."""
    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    items = _signed_items(ring, 20)
    rng = np.random.default_rng(11)
    for i in range(0, 20, 3):  # every third lane becomes garbage
        items[i] = (items[i][0], items[i][1],
                    bytes(rng.integers(0, 256, 64, dtype=np.uint8)))
    ok_chal = _chal_verify(host, table, items)
    (idx, r, s, k), pv, n = host.pack_wire_indexed(items, table)
    semi = make_semiwire_verify_fn()
    ok_semi = (np.asarray(semi(
        jnp.asarray(idx), jnp.asarray(r), jnp.asarray(s), jnp.asarray(k),
        *table.arrays())) & pv)[:n]
    np.testing.assert_array_equal(ok_chal, ok_semi)


def test_chalwire_unknown_pub_raises(ring_table):
    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    stranger = KeyRing.deterministic(1, namespace=b"stranger")[0]
    d = bytes(32)
    items = [(stranger.public, d, stranger.sign_digest(d))]
    with pytest.raises(ValueError):
        host.pack_wire_challenge(items, table)


def test_chalwire_requires_32_byte_digests(ring_table):
    """The device hash has a fixed 96-byte preimage, so the packer hard-
    rejects other digest widths — and TpuWireVerifier must route such
    items through the host-hashed full wire path with oracle-equal
    verdicts (the fallback the packer's error forces)."""
    from hyperdrive_tpu.ops.ed25519_wire import TpuWireVerifier

    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    d20 = b"\x07" * 20
    items = [(ring[0].public, d20, ring[0].sign_digest(d20))]
    with pytest.raises(ValueError):
        host.pack_wire_challenge(items, table)
    wv = TpuWireVerifier(buckets=(64,), table=table, backend="xla")
    got = wv.verify_signatures(items)
    assert got.tolist() == [host_ed.verify(ring[0].public, d20,
                                           items[0][2])] == [True]


def test_chalwire_pallas_interpret_matches_xla(ring_table):
    """chalwire_verify_pallas (the path the TPU engine verifier takes:
    XLA challenge leg + Mosaic ladder) in interpret mode, against the
    all-XLA path — identical verdicts lane for lane, tampered lanes
    included."""
    from hyperdrive_tpu.ops.ed25519_wire import chalwire_verify_pallas

    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    items = _signed_items(ring, 12, seed=31)
    items[2] = (items[2][0], items[2][1],
                items[2][2][:63] + bytes([items[2][2][63] ^ 1]))
    items[9] = (ring[3].public, items[9][1], items[9][2])  # wrong sender
    (idx, r, s, m), prevalid, n = host.pack_wire_challenge(items, table)
    args = (jnp.asarray(idx), jnp.asarray(r), jnp.asarray(s),
            jnp.asarray(m), *table.arrays_chal())
    ok_pallas = (np.asarray(
        chalwire_verify_pallas(*args, block=64, interpret=True)
    ) & prevalid)[:n]
    ok_xla = (np.asarray(make_chalwire_verify_fn()(*args)) & prevalid)[:n]
    np.testing.assert_array_equal(ok_pallas, ok_xla)
    assert not ok_pallas[2] and not ok_pallas[9]
    assert ok_pallas.sum() == n - 2


def test_chalwire_empty_batch(ring_table):
    _, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    (idx, r, s, m), prevalid, n = host.pack_wire_challenge([], table)
    assert n == 0 and not prevalid.any()


def test_chal_verifier_drives_consensus_end_to_end():
    """The challenge-path verifier inside the full engine: a signed burst
    network whose every settle window rides the chalwire kernels
    (small_window_host=False pins the device path at these tiny window
    sizes — the ADVICE round-3 knob), committing identically to a
    host-verified run. Mirrors the reference's full-network integration
    (/root/reference/replica/replica_test.go:372-430) with the round-4
    wire format underneath."""
    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.ops.ed25519_wire import TpuWireVerifier

    n, target, seed = 4, 3, 99
    ring = KeyRing.deterministic(n, namespace=b"sim-%d" % seed)
    table = ValidatorTable([ring[i].public for i in range(n)])
    wv = TpuWireVerifier(buckets=(64, 256), table=table, backend="xla")
    run = Simulation(
        n=n, target_height=target, seed=seed, sign=True, burst=True,
        batch_verifier=wv, small_window_host=False,
    ).run(max_steps=200_000)
    assert run.completed, run.heights
    run.assert_safety()
    host = Simulation(
        n=n, target_height=target, seed=seed, sign=True, burst=True
    ).run(max_steps=200_000)
    assert run.commits == host.commits


def test_chalwire_per_round_digest_broadcast(ring_table):
    """The 68 B/lane deployment shape: with_m=False, digests shipped
    per-round and broadcast to lanes on device via the library's
    make_challenge_round_fn (the exact executable bench.py's sustained
    headline uses) — verdicts identical to per-lane m rows, including
    the bucket-padding lanes beyond rounds*validators."""
    from hyperdrive_tpu.ops.ed25519_wire import (
        make_challenge_round_fn,
        make_semiwire_verify_fn,
    )

    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    rounds, validators = 4, 8
    rng = np.random.default_rng(23)
    m_round = rng.integers(0, 256, (rounds, 32), dtype=np.uint8)
    items = []
    for r in range(rounds):
        for v in range(validators):
            d = bytes(m_round[r])
            items.append((ring[v].public, d, ring[v].sign_digest(d)))
    items[5] = (items[5][0], items[5][1], items[6][2])  # cross-lane sig

    (idx, rr, ss, _), prevalid, n = host.pack_wire_challenge(
        items, table, with_m=False)

    chal_leg = make_challenge_round_fn(validators)
    k_rows = chal_leg(jnp.asarray(idx), jnp.asarray(rr),
                      jnp.asarray(m_round), table.rows)
    semi = make_semiwire_verify_fn()
    ok = (np.asarray(semi(
        jnp.asarray(idx), jnp.asarray(rr), jnp.asarray(ss), k_rows,
        *table.arrays())) & prevalid)[:n]
    ok_ref = _chal_verify(host, table, items)
    np.testing.assert_array_equal(ok, ok_ref)
    assert not ok[5] and ok.sum() == n - 1


# --------------------------------------------- grouped engine wire format


def test_grouped_chal_matches_per_lane_and_oracle(ring_table):
    """The 69 B/lane grouped engine format (deduped digest table + a
    one-byte lane index, M gathered on device) must agree bit-for-bit
    with the per-lane chal path and the host oracle — tampered lanes
    included."""
    from hyperdrive_tpu.ops.ed25519_wire import make_challenge_grouped_fn

    ring, table = ring_table
    host = Ed25519WireHost(buckets=(64,))
    rng = np.random.default_rng(41)
    uniq = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            for _ in range(3)]
    items = []
    for i in range(20):
        v, d = i % 8, uniq[i % 3]
        items.append((ring[v].public, d, ring[v].sign_digest(d)))
    items[4] = (items[4][0], items[4][1],
                items[4][2][:63] + bytes([items[4][2][63] ^ 1]))
    items[5] = (ring[(5 + 1) % 8].public, items[5][1], items[5][2])

    (idx, r, s, _), prevalid, n = host.pack_wire_challenge(
        items, table, with_m=False)
    m_idx, m_uniq, u = host.group_digests(items, len(prevalid))
    assert u == 3 and m_uniq.shape == (host.M_BUCKETS[0], 32)
    k = make_challenge_grouped_fn()(
        jnp.asarray(idx), jnp.asarray(r), jnp.asarray(m_idx),
        jnp.asarray(m_uniq), table.rows)
    semi = make_semiwire_verify_fn()
    ok = (np.asarray(semi(
        jnp.asarray(idx), jnp.asarray(r), jnp.asarray(s), k,
        *table.arrays())) & prevalid)[:n]
    want = np.array([host_ed.verify(p, d, sig) for p, d, sig in items])
    np.testing.assert_array_equal(ok, want)
    ok_perlane = _chal_verify(host, table, items)
    np.testing.assert_array_equal(ok, ok_perlane)
    assert not want[4] and not want[5] and want.sum() == n - 2


def test_verifier_routes_grouped_and_counts_bytes(ring_table):
    """TpuWireVerifier ships consensus-shaped chunks (few distinct
    digests) in the grouped 69 B/lane format and accounts engine
    bytes/lane."""
    from hyperdrive_tpu.ops.ed25519_wire import TpuWireVerifier

    ring, table = ring_table
    wv = TpuWireVerifier(buckets=(64,), table=table, backend="xla")
    uniq = [bytes([7]) * 32, bytes([9]) * 32]
    items = []
    for v in range(24):
        d = uniq[v % 2]
        items.append((ring[v % 8].public, d, ring[v % 8].sign_digest(d)))
    items[3] = (items[3][0], items[3][1], items[3][2][:32] + bytes(32))
    got = wv.verify_signatures(items)
    want = [host_ed.verify(p, d, s) for p, d, s in items]
    assert got.tolist() == want and not want[3]
    assert wv.stats["lanes_grouped"] == 24
    assert wv.stats["lanes_chal"] == 0 and wv.stats["lanes_wire"] == 0
    assert wv.stats["format_bytes"] == 69 * 24 + 32 * 2
    assert abs(wv.bytes_per_lane() - (69 * 24 + 64) / 24) < 1e-9
    wv.reset_stats()
    assert wv.bytes_per_lane() == 0.0


def test_verifier_falls_back_per_lane_above_group_cap(ring_table):
    """A chunk with more distinct digests than the one-byte index can
    address rides per-lane digest rows (100 B/lane), verdicts unchanged.
    The cap is shrunk so the fallback triggers at test-size chunks."""
    from hyperdrive_tpu.ops.ed25519_wire import TpuWireVerifier

    ring, table = ring_table
    wv = TpuWireVerifier(buckets=(64,), table=table, backend="xla")
    wv.host.M_GROUP_CAP = 4  # instance override: force the fallback
    items = _signed_items(ring, 24, seed=53)  # 24 distinct digests > 4
    assert wv.host.group_digests(items, 64) is None
    got = wv.verify_signatures(items)
    assert got.all()
    assert wv.stats["lanes_chal"] == 24
    assert wv.stats["lanes_grouped"] == 0 and wv.stats["lanes_wire"] == 0
    assert wv.stats["format_bytes"] == 100 * 24
