"""Round-robin scheduler: fairness, determinism, input validation.

Mirrors scheduler/scheduler_test.go.
"""

import pytest

from hyperdrive_tpu.scheduler import RoundRobin


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def test_single_signatory_always_elected():
    rr = RoundRobin([sig(1)])
    for h in range(1, 20):
        for r in range(5):
            assert rr.schedule(h, r) == sig(1)


def test_modular_fairness():
    sigs = [sig(i) for i in range(1, 6)]
    rr = RoundRobin(sigs)
    for h in range(1, 30):
        for r in range(10):
            assert rr.schedule(h, r) == sigs[(h + r) % 5]


def test_rotates_with_round():
    sigs = [sig(i) for i in range(1, 4)]
    rr = RoundRobin(sigs)
    elected = {rr.schedule(1, r) for r in range(3)}
    assert elected == set(sigs)


def test_empty_set_raises():
    with pytest.raises(ValueError):
        RoundRobin([]).schedule(1, 0)


@pytest.mark.parametrize("h", [0, -1])
def test_invalid_height_raises(h):
    with pytest.raises(ValueError):
        RoundRobin([sig(1)]).schedule(h, 0)


def test_invalid_round_raises():
    with pytest.raises(ValueError):
        RoundRobin([sig(1)]).schedule(1, -1)


def test_uint64_wraparound_parity():
    # Go computes uint64(height)+uint64(round) with wraparound
    # (scheduler/scheduler.go:52); int64 max inputs must not crash and must
    # stay deterministic.
    sigs = [sig(i) for i in range(1, 8)]
    rr = RoundRobin(sigs)
    h = (1 << 63) - 1
    r = (1 << 63) - 1
    idx = (((h & ((1 << 64) - 1)) + (r & ((1 << 64) - 1))) & ((1 << 64) - 1)) % 7
    assert rr.schedule(h, r) == sigs[idx]


def test_mutating_input_list_does_not_affect_schedule():
    sigs = [sig(i) for i in range(1, 4)]
    rr = RoundRobin(sigs)
    before = rr.schedule(1, 0)
    sigs[:] = [sig(9)] * 3
    assert rr.schedule(1, 0) == before
