"""Round-robin scheduler: fairness, determinism, input validation.

Mirrors scheduler/scheduler_test.go.
"""

import pytest

from hyperdrive_tpu.scheduler import RoundRobin


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def test_single_signatory_always_elected():
    rr = RoundRobin([sig(1)])
    for h in range(1, 20):
        for r in range(5):
            assert rr.schedule(h, r) == sig(1)


def test_modular_fairness():
    sigs = [sig(i) for i in range(1, 6)]
    rr = RoundRobin(sigs)
    for h in range(1, 30):
        for r in range(10):
            assert rr.schedule(h, r) == sigs[(h + r) % 5]


def test_rotates_with_round():
    sigs = [sig(i) for i in range(1, 4)]
    rr = RoundRobin(sigs)
    elected = {rr.schedule(1, r) for r in range(3)}
    assert elected == set(sigs)


def test_empty_set_raises():
    with pytest.raises(ValueError):
        RoundRobin([]).schedule(1, 0)


@pytest.mark.parametrize("h", [0, -1])
def test_invalid_height_raises(h):
    with pytest.raises(ValueError):
        RoundRobin([sig(1)]).schedule(h, 0)


def test_invalid_round_raises():
    with pytest.raises(ValueError):
        RoundRobin([sig(1)]).schedule(1, -1)


def test_uint64_wraparound_parity():
    # Go computes uint64(height)+uint64(round) with wraparound
    # (scheduler/scheduler.go:52); int64 max inputs must not crash and must
    # stay deterministic.
    sigs = [sig(i) for i in range(1, 8)]
    rr = RoundRobin(sigs)
    h = (1 << 63) - 1
    r = (1 << 63) - 1
    idx = (((h & ((1 << 64) - 1)) + (r & ((1 << 64) - 1))) & ((1 << 64) - 1)) % 7
    assert rr.schedule(h, r) == sigs[idx]


def test_mutating_input_list_does_not_affect_schedule():
    sigs = [sig(i) for i in range(1, 4)]
    rr = RoundRobin(sigs)
    before = rr.schedule(1, 0)
    sigs[:] = [sig(9)] * 3
    assert rr.schedule(1, 0) == before


def test_round_robin_fairness_over_heights_and_rounds():
    # Reference: scheduler_test.go modular fairness — over any n*k
    # consecutive (height+round) coordinates each signatory is elected
    # exactly k times.
    sigs = [bytes([i]) * 32 for i in range(7)]
    rr = RoundRobin(sigs)
    from collections import Counter

    counts = Counter(rr.schedule(h, 0) for h in range(1, 7 * 11 + 1))
    assert set(counts.values()) == {11}
    # Fixing the height and walking rounds cycles the same way.
    counts = Counter(rr.schedule(5, r) for r in range(7 * 3))
    assert set(counts.values()) == {3}


def test_round_robin_height_round_interchangeable():
    sigs = [bytes([i]) * 32 for i in range(5)]
    rr = RoundRobin(sigs)
    for h in range(1, 20):
        for r in range(6):
            assert rr.schedule(h, r) == rr.schedule(h + r, 0)
