"""Device-resident vote grids: scatter/tally kernel + TallyView semantics.

The grid must be an exact device image of the host vote logs: counts equal
hand-counted quorums, resets wipe exactly one replica, state accumulates
across launches, and the TallyView declines every query the launch didn't
provably answer.
"""

import numpy as np
import pytest

from hyperdrive_tpu.ops.votegrid import (
    PRECOMMIT_PLANE,
    PREVOTE_PLANE,
    TallyView,
    VoteGrid,
)
from hyperdrive_tpu.types import NIL_VALUE


def words(value: bytes) -> np.ndarray:
    return np.frombuffer(value, dtype="<i4").astype(np.int32)


V_A = b"\xaa" * 32
V_B = b"\xbb" * 32


def launch(grid, rows, n, *, reset=None, targets=None, l28=None, f=1):
    """rows: list of (rep, plane, slot, val, value_bytes)."""
    idx = np.array([r[:4] for r in rows], dtype=np.int32).reshape(-1, 4)
    w = (
        np.stack([words(r[4]) for r in rows])
        if rows
        else np.zeros((0, 8), dtype=np.int32)
    )
    R = grid.R
    tv = np.zeros((n, R), dtype=bool)
    tg = np.zeros((n, R, 8), dtype=np.int32)
    for rep, rnd, val in targets or ():
        tg[rep, rnd] = words(val)
        tv[rep, rnd] = True
    l28_slot = np.full(n, -1, dtype=np.int32)
    l28_target = np.zeros((n, 8), dtype=np.int32)
    for rep, rnd, val in l28 or ():
        l28_slot[rep] = rnd
        l28_target[rep] = words(val)
    return grid.update_and_tally(
        idx,
        w,
        np.asarray(reset if reset is not None else np.zeros(n, dtype=bool)),
        tg,
        tv,
        l28_slot,
        l28_target,
        np.full(n, f, dtype=np.int32),
    )


def test_counts_match_hand_tally():
    n, V = 3, 7
    grid = VoteGrid(n, V, r_slots=4, buckets=(16,))
    rows = [
        # Replica 0, prevotes round 0: 5 for A, 1 nil, 1 for B.
        *[(0, PREVOTE_PLANE, 0, v, V_A) for v in range(5)],
        (0, PREVOTE_PLANE, 0, 5, NIL_VALUE),
        (0, PREVOTE_PLANE, 0, 6, V_B),
        # Replica 0, precommits round 0: 3 for A.
        *[(0, PRECOMMIT_PLANE, 0, v, V_A) for v in range(3)],
        # Replica 2, prevotes round 1: 2 nil.
        (2, PREVOTE_PLANE, 1, 0, NIL_VALUE),
        (2, PREVOTE_PLANE, 1, 1, NIL_VALUE),
    ]
    counts = launch(
        grid, rows, n, targets=[(0, 0, V_A), (2, 1, V_A)], f=2
    )
    assert counts["matching"][0, PREVOTE_PLANE, 0] == 5
    assert counts["nil"][0, PREVOTE_PLANE, 0] == 1
    assert counts["total"][0, PREVOTE_PLANE, 0] == 7
    assert counts["matching"][0, PRECOMMIT_PLANE, 0] == 3
    assert counts["total"][0, PRECOMMIT_PLANE, 0] == 3
    assert counts["nil"][2, PREVOTE_PLANE, 1] == 2
    assert counts["matching"][2, PREVOTE_PLANE, 1] == 0
    # Quorum at f=2 needs 5.
    assert bool(counts["quorum_matching"][0, PREVOTE_PLANE, 0])
    assert bool(counts["quorum_any"][0, PREVOTE_PLANE, 0])
    assert not bool(counts["quorum_matching"][0, PRECOMMIT_PLANE, 0])
    # Untouched replica 1 is all zeros.
    assert counts["total"][1].sum() == 0


def test_accumulation_and_reset():
    n, V = 2, 5
    grid = VoteGrid(n, V, r_slots=2, buckets=(8,))
    launch(grid, [(0, PREVOTE_PLANE, 0, 0, V_A)], n, targets=[(0, 0, V_A)])
    launch(grid, [(0, PREVOTE_PLANE, 0, 1, V_A)], n, targets=[(0, 0, V_A)])
    counts = launch(
        grid,
        [(1, PREVOTE_PLANE, 0, 2, V_A)],
        n,
        targets=[(0, 0, V_A), (1, 0, V_A)],
    )
    # Replica 0 accumulated both earlier launches; replica 1 only its own.
    assert counts["matching"][0, PREVOTE_PLANE, 0] == 2
    assert counts["matching"][1, PREVOTE_PLANE, 0] == 1
    # Reset replica 0 (height advanced): its planes wipe, replica 1 keeps.
    reset = np.array([True, False])
    counts = launch(
        grid, [], n, reset=reset, targets=[(0, 0, V_A), (1, 0, V_A)]
    )
    assert counts["total"][0].sum() == 0
    assert counts["matching"][1, PREVOTE_PLANE, 0] == 1
    # Re-scatter after reset starts fresh.
    counts = launch(
        grid, [(0, PREVOTE_PLANE, 0, 4, V_B)], n, targets=[(0, 0, V_B)]
    )
    assert counts["matching"][0, PREVOTE_PLANE, 0] == 1
    assert counts["total"][0, PREVOTE_PLANE, 0] == 1


def test_l28_cross_round_lane():
    n, V = 1, 5
    grid = VoteGrid(n, V, r_slots=4, buckets=(8,))
    # Prevotes for A at round 0; round 2's proposal re-proposes A with
    # valid_round 0 — the L28 query is "prevotes at round 0 matching A".
    rows = [(0, PREVOTE_PLANE, 0, v, V_A) for v in range(3)]
    counts = launch(
        grid, rows, n, targets=[(0, 0, V_B)], l28=[(0, 0, V_A)], f=1
    )
    # Per-round target (B) doesn't match the A prevotes...
    assert counts["matching"][0, PREVOTE_PLANE, 0] == 0
    # ...but the L28 lane counts them against A.
    assert counts["l28"][0] == 3
    assert bool(counts["l28_quorum"][0])


def test_empty_launch_and_bucket_padding():
    grid = VoteGrid(2, 3, r_slots=2, buckets=(4,))
    counts = launch(grid, [], 2)
    assert counts["total"].sum() == 0
    # 5 rows > bucket 4: next multiple is used, all rows land.
    rows = [(0, PREVOTE_PLANE, 0, v % 3, V_A) for v in range(3)]
    rows += [(1, PREVOTE_PLANE, 1, v, V_A) for v in range(2)]
    counts = launch(grid, rows, 2, targets=[(0, 0, V_A), (1, 1, V_A)])
    assert counts["total"][0, PREVOTE_PLANE, 0] == 3
    assert counts["total"][1, PREVOTE_PLANE, 1] == 2


def make_view(counts, rep=0, height=1, R=4, targets=None, l28_round=-1,
              l28_value=b"", dirty=frozenset()):
    return TallyView(rep, height, counts, R, targets or {}, l28_round,
                     l28_value, dirty)


def test_view_answers_and_declines():
    n, V = 1, 5
    grid = VoteGrid(n, V, r_slots=4, buckets=(8,))
    rows = [(0, PREVOTE_PLANE, 0, v, V_A) for v in range(3)]
    rows += [(0, PRECOMMIT_PLANE, 0, v, V_A) for v in range(2)]
    rows += [(0, PREVOTE_PLANE, 1, 0, NIL_VALUE)]
    counts = launch(grid, rows, n, targets=[(0, 0, V_A)])
    view = make_view(counts, targets={0: V_A})

    assert view.prevotes_for(0, V_A) == 3
    assert view.precommits_for(0, V_A) == 2
    assert view.prevote_total(0) == 3
    assert view.precommit_total(0) == 2
    assert view.prevotes_for(1, NIL_VALUE) == 1
    # Declines: target value the launch never compared against.
    assert view.prevotes_for(0, V_B) is None
    # Declines: round outside the slot window.
    assert view.prevotes_for(99, V_A) is None
    assert view.precommit_total(99) is None
    # Declines: dirty (plane, round).
    dirty_view = make_view(
        counts, targets={0: V_A}, dirty={(PREVOTE_PLANE, 0)}
    )
    assert dirty_view.prevotes_for(0, V_A) is None
    assert dirty_view.prevote_total(0) is None
    # The other plane of the same round is unaffected.
    assert dirty_view.precommits_for(0, V_A) == 2


def test_view_l28_lane_requires_exact_pair():
    n = 1
    grid = VoteGrid(n, 4, r_slots=4, buckets=(8,))
    rows = [(0, PREVOTE_PLANE, 1, v, V_A) for v in range(2)]
    counts = launch(grid, rows, n, l28=[(0, 1, V_A)])
    view = make_view(counts, l28_round=1, l28_value=V_A)
    assert view.prevotes_for(1, V_A) == 2  # via the L28 lane
    assert view.prevotes_for(2, V_A) is None  # wrong round
    assert view.prevotes_for(1, V_B) is None  # wrong value


@pytest.mark.requires_shard_map
def test_sharded_grid_matches_unsharded():
    # 8-device CPU mesh: validator axis sharded, scatter rows routed by
    # global index, counts psum'd — must equal the single-device grid
    # bit for bit, across accumulation and resets.
    import jax

    from hyperdrive_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(hr=1, val=8)
    n, V = 3, 16
    plain = VoteGrid(n, V, r_slots=2, buckets=(32,))
    shard = VoteGrid(n, V, r_slots=2, buckets=(32,), mesh=mesh)

    rows1 = [(0, PREVOTE_PLANE, 0, v, V_A) for v in range(9)]
    rows1 += [(1, PRECOMMIT_PLANE, 1, v, NIL_VALUE) for v in (3, 7, 11, 15)]
    rows2 = [(0, PREVOTE_PLANE, 0, v, V_B) for v in range(9, 14)]
    rows2 += [(2, PREVOTE_PLANE, 0, 15, V_A)]

    targets = [(0, 0, V_A), (1, 1, V_A), (2, 0, V_A)]
    l28 = [(0, 0, V_A)]
    for g in (plain, shard):
        launch(g, rows1, n, targets=targets, l28=l28, f=2)
    reset = np.array([False, True, False])
    out = [
        launch(g, rows2, n, reset=reset, targets=targets, l28=l28, f=2)
        for g in (plain, shard)
    ]
    for key in out[0]:
        assert np.array_equal(out[0][key], out[1][key]), key
    # Sanity on content, not just agreement: replica 1 was reset, replica
    # 0 accumulated 9 A-votes + 5 B-votes, L28 counted the A prevotes.
    c = out[1]
    assert c["total"][1].sum() == 0
    assert c["matching"][0, PREVOTE_PLANE, 0] == 9
    assert c["total"][0, PREVOTE_PLANE, 0] == 14
    assert c["l28"][0] == 9
