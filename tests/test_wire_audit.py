"""Wire-audit corpus: registry closure + seeded structure-aware fuzzing.

Two contracts, both jax-free and fully deterministic:

1. **Closure** — every tag in the ``@wire_codec`` registry has a seed
   sample here (this file is what ``--wire-report``'s roundtrip-test
   column points at). A new codec that registers without adding a
   sample fails ``test_registry_closure``; a codec that never registers
   fails HD009 in strict lint. Between the two, there is no way to add
   a frame family without fuzz coverage.
2. **Decode totality** — for every registered codec, >= 1000 seeded
   byte-level mutations of its canonical samples (truncate / extend /
   bitflip / tag-swap) must either raise a TYPED error (SerdeError,
   ValueError, or the sanitizer's HDS005) or decode to a value whose
   re-encoding is a fixpoint (encode(decode(x)) re-decodes to the same
   bytes). Any other exception is a decoder crash — the bug class this
   corpus exists to keep extinct.

``HD_SANITIZE=1`` (the conftest default) arms the HDS005 budget reader
under every decode, so the fuzz also proves the per-family budgets
never misfire on honest frames.
"""

from __future__ import annotations

import random

import pytest

from hyperdrive_tpu.analysis.annotations import (
    WIRE_BUDGETS,
    WIRE_CODECS,
    wire_budget_for,
)
from hyperdrive_tpu.analysis.sanitizer import SanitizerError, maybe_wire_reader
from hyperdrive_tpu.campaign import CampaignConfig
from hyperdrive_tpu.campaign.record import CampaignRecord
from hyperdrive_tpu.certificates import (
    QuorumCertificate,
    marshal_certificate,
    unmarshal_certificate,
)
from hyperdrive_tpu.codec import SerdeError, Writer
from hyperdrive_tpu.crypto.shamir import (
    decode_share_bundle,
    encode_share_bundle,
)
from hyperdrive_tpu.epochs import (
    EpochProof,
    marshal_epoch_proof,
    unmarshal_epoch_proof,
)
from hyperdrive_tpu.messages import (
    Precommit,
    Prevote,
    Propose,
    Timeout,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.ops.merkle import MerkleProof
from hyperdrive_tpu.obs.tracectx import decode_stamp, encode_stamp
from hyperdrive_tpu.parallel.service import (
    STATUS_COMMITTED,
    STATUS_NO_QUORUM,
    STATUS_SHED,
    decode_hello_ack,
    decode_metrics_reply,
    decode_proof,
    decode_request,
    decode_result,
    encode_hello,
    encode_hello_ack,
    encode_metrics_reply,
    encode_metrics_request,
    encode_proof,
    encode_query,
    encode_result,
    encode_submit,
)
from hyperdrive_tpu.state import State
from hyperdrive_tpu.types import MessageType

#: The full set of deliberate decode rejections. SanitizerError covers
#: HDS005 budget raises under HD_SANITIZE; everything else escaping a
#: decoder is a crash and fails the corpus.
TYPED_ERRORS = (SerdeError, ValueError, SanitizerError)

#: Seeded mutations per codec tag (the acceptance floor is 1000).
N_MUTATIONS = 1000


# ------------------------------------------------------------ seed values


def _propose() -> Propose:
    return Propose(height=7, round=2, valid_round=1, value=b"\x11" * 32,
                   sender=b"\x22" * 32, payload=b"xyz",
                   signature=b"\x33" * 64)


def _prevote() -> Prevote:
    return Prevote(height=7, round=2, value=b"\x11" * 32,
                   sender=b"\x22" * 32, signature=b"\x44" * 64)


def _precommit() -> Precommit:
    return Precommit(height=7, round=2, value=b"\x11" * 32,
                     sender=b"\x22" * 32, signature=b"\x55" * 64)


def _timeout() -> Timeout:
    return Timeout(message_type=MessageType.PREVOTE, height=7, round=2)


def _cert() -> QuorumCertificate:
    return QuorumCertificate(height=7, round=2, value_digest=b"\x66" * 32,
                             signers=b"\x0b", transcript=b"\x77" * 32,
                             binding=b"\x88" * 32, agg_sig=b"")


def _epoch_proof() -> EpochProof:
    return EpochProof(epoch=3, prev_set_digest=b"\x99" * 32,
                      next_set_digest=b"\xaa" * 32,
                      next_signatories=(b"\x01" * 32, b"\x02" * 32),
                      cert=_cert())


def _campaign_record() -> CampaignRecord:
    cfg = CampaignConfig(
        family="storm", seed=7, validators=64, committee_size=16,
        epochs=4, epoch_length=2, attackers=4, waves=3, wave_votes=2,
        attack_rate=4, sybils=8, budget_milli=200, grind_width=2,
    )
    return CampaignRecord.capture(
        cfg, {"family": "storm", "waves": [[3, 48, 0, 0]],
              "violations": []},
    )


def _merkle_proof() -> MerkleProof:
    return MerkleProof(height=7, account=5, balance=100, stake=10,
                       prev_root=b"\xbb" * 32,
                       digest=tuple(range(8)),
                       siblings=((0, 1, 2, 3), (4, 5, 6, 7)))


def _obj_bytes(obj, rem=None) -> bytes:
    """marshal-method objects (Propose, State, ScenarioRecord, ...)."""
    w = Writer() if rem is None else Writer(rem=rem)
    obj.marshal(w)
    return w.data()


def _fn_bytes(marshal_fn, obj) -> bytes:
    """marshal-function pairs (certificates, epochs, envelopes)."""
    w = Writer()
    marshal_fn(obj, w)
    return w.data()


def _reencode_request(req) -> bytes:
    kind = req[0]
    if kind == "hello":  # ("hello", name, f, signatories, t0)
        return encode_hello(req[1], req[3], req[2], t0=req[4])
    if kind == "submit":  # ("submit", req_id, h, r, value, gen, rows)
        return encode_submit(req[1], req[2], req[3], req[4], req[6],
                             generation=req[5])
    if kind == "metrics":  # ("metrics", req_id)
        return encode_metrics_request(req[1])
    return encode_query(req[1], req[2])  # ("query", req_id, account)


def _reencode_metrics_reply(res) -> bytes:
    req_id, status, text = res
    return encode_metrics_reply(req_id, status, text or "")


def _reencode_result(res) -> bytes:
    req_id, status, mask, cert, root = res
    return encode_result(req_id, status, len(mask), mask, cert=cert,
                         root=root)


def _reencode_proof(res) -> bytes:
    req_id, status, proof = res
    return encode_proof(req_id, status, proof)


# -------------------------------------------------------------- the table
#
# tag -> (decode: bytes -> value, reencode: value -> bytes, seed frames).
# decode takes raw frame bytes (through maybe_wire_reader where the
# production seam does, so HD_SANITIZE budgets are exercised);
# reencode(decode(seed)) == seed for every canonical seed, and
# encode-after-decode is a fixpoint for any mutant that still decodes.
# Entries of None are built lazily by their own test below (tmp_path /
# deferred imports).

SAMPLES = {
    "msg.propose": (
        lambda b: Propose.unmarshal(maybe_wire_reader("msg.propose", b)),
        _obj_bytes,
        [_obj_bytes(_propose())],
    ),
    "msg.prevote": (
        lambda b: Prevote.unmarshal(maybe_wire_reader("msg.prevote", b)),
        _obj_bytes,
        [_obj_bytes(_prevote())],
    ),
    "msg.precommit": (
        lambda b: Precommit.unmarshal(
            maybe_wire_reader("msg.precommit", b)
        ),
        _obj_bytes,
        [_obj_bytes(_precommit())],
    ),
    "msg.timeout": (
        lambda b: Timeout.unmarshal(maybe_wire_reader("msg.timeout", b)),
        _obj_bytes,
        [_obj_bytes(_timeout())],
    ),
    "msg.envelope": (
        lambda b: unmarshal_message(maybe_wire_reader("msg.envelope", b)),
        lambda m: _fn_bytes(marshal_message, m),
        [_fn_bytes(marshal_message, _propose()),
         _fn_bytes(marshal_message, _prevote()),
         _fn_bytes(marshal_message, _precommit()),
         _fn_bytes(marshal_message, _timeout())],
    ),
    "cert.quorum": (
        lambda b: unmarshal_certificate(
            maybe_wire_reader("cert.quorum", b)
        ),
        lambda c: _fn_bytes(marshal_certificate, c),
        [_fn_bytes(marshal_certificate, _cert())],
    ),
    "epoch.proof": (
        lambda b: unmarshal_epoch_proof(
            maybe_wire_reader("epoch.proof", b)
        ),
        lambda p: _fn_bytes(marshal_epoch_proof, p),
        [_fn_bytes(marshal_epoch_proof, _epoch_proof())],
    ),
    "shamir.bundle": (
        decode_share_bundle,
        encode_share_bundle,
        [encode_share_bundle([[(1, 5), (2, 9)], [(1, 3), (2, 4)]])],
    ),
    "service.hello": (
        decode_request,
        _reencode_request,
        [encode_hello("tenant-a", [b"\x01" * 32, b"\x02" * 32], 0),
         encode_hello("tenant-b", [b"\x01" * 32], 0, t0=12345.625)],
    ),
    "service.hello.ack": (
        decode_hello_ack,
        lambda v: encode_hello_ack(*v),
        [encode_hello_ack(12345.625, 12345.75, 7),
         encode_hello_ack(0.0, 0.0, 0)],
    ),
    "service.metrics": (
        decode_request,
        _reencode_request,
        [encode_metrics_request(9)],
    ),
    "service.metrics.reply": (
        decode_metrics_reply,
        _reencode_metrics_reply,
        [encode_metrics_reply(9, STATUS_COMMITTED,
                              "# TYPE hd_x counter\nhd_x 1\n"),
         encode_metrics_reply(9, STATUS_SHED)],
    ),
    "trace.ctx": (
        decode_stamp,
        lambda v: encode_stamp(*v),
        [encode_stamp(1, 1, 0),
         encode_stamp(3, 512, (2 << 32) | 41)],
    ),
    "service.submit": (
        decode_request,
        _reencode_request,
        [encode_submit(9, 7, 2, b"\x11" * 32,
                       [(b"\x22" * 32, b"\x33" * 64)])],
    ),
    "service.query": (
        decode_request,
        _reencode_request,
        [encode_query(9, 5)],
    ),
    "service.result": (
        decode_result,
        _reencode_result,
        [encode_result(9, STATUS_COMMITTED, 3, [True, False, True],
                       cert=_cert(), root=b"\xcc" * 32),
         encode_result(9, STATUS_NO_QUORUM, 0, [])],
    ),
    "service.proof": (
        decode_proof,
        _reencode_proof,
        [encode_proof(9, STATUS_COMMITTED, _merkle_proof()),
         encode_proof(9, STATUS_NO_QUORUM)],
    ),
    "campaign.record": (
        CampaignRecord.load,
        lambda rec: _obj_bytes(rec, rem=1 << 20),
        [_obj_bytes(_campaign_record(), rem=1 << 20)],
    ),
    "state.checkpoint": (
        lambda b: State.unmarshal(
            maybe_wire_reader("state.checkpoint", b, rem=1 << 28)
        ),
        _obj_bytes,
        [_obj_bytes(State())],
    ),
    "process.checkpoint": (None, None, None),  # fresh-Process fixture
    "scenario.record": (None, None, None),     # deferred harness import
    "flight.record": (None, None, None),       # tmp_path file framing
}


def _process_sample():
    from hyperdrive_tpu.process import Process
    from hyperdrive_tpu.utils.checkpoint import (
        checkpoint_bytes,
        restore_bytes,
    )

    def decode(data):
        # Restoring IS the decode; re-checkpointing the restored process
        # is the canonical re-encode, so decode returns bytes and
        # reencode is the identity.
        proc = Process(whoami=b"\x01" * 32, f=1)
        restore_bytes(proc, data)
        return checkpoint_bytes(proc)

    return decode, lambda data: data, [
        checkpoint_bytes(Process(whoami=b"\x01" * 32, f=1))
    ]


def _scenario_sample():
    from hyperdrive_tpu.harness.sim import ScenarioRecord

    rec = ScenarioRecord(seed=1, n=4, f=1, target_height=2)
    rec.signatories = [bytes([i + 1]) * 32 for i in range(4)]
    rec.messages = [(0, _prevote()), (1, _timeout())]
    rec.bursts = [2]
    rec.batch_ingest = False

    def decode(b):
        return ScenarioRecord.unmarshal(
            maybe_wire_reader("scenario.record", b, rem=1 << 30)
        )

    return decode, lambda r: _obj_bytes(r, rem=1 << 30), [
        _obj_bytes(rec, rem=1 << 30)
    ]


def _flight_sample(tmp_path):
    from hyperdrive_tpu.transport import FlightRecorder

    rec = FlightRecorder()
    rec.record(_prevote())
    rec.record(_precommit())

    def decode(b):
        p = tmp_path / "flight.bin"
        p.write_bytes(b)
        return FlightRecorder.load(str(p))

    def reencode(msgs):
        out = FlightRecorder()
        for m in msgs:
            out.record(m)
        return b"".join(out.frames)

    return decode, reencode, [b"".join(rec.frames)]


# -------------------------------------------------------------- the fuzz


def _mutations(tag: str, seeds, n: int):
    """Deterministic mutation stream: per index, a seeded RNG picks a
    seed frame and one of truncate / extend / bitflip / tag-swap."""
    for i in range(n):
        rng = random.Random(f"wire-fuzz:{tag}:{i}")
        base = seeds[rng.randrange(len(seeds))]
        kind = i % 4
        if kind == 0 and base:  # truncate
            yield base[: rng.randrange(len(base))]
        elif kind == 1:  # extend with junk
            yield base + bytes(
                rng.randrange(256) for _ in range(1 + rng.randrange(16))
            )
        elif kind == 2 and base:  # bitflip
            pos = rng.randrange(len(base))
            mutated = bytearray(base)
            mutated[pos] ^= 1 << rng.randrange(8)
            yield bytes(mutated)
        elif base:  # tag-swap: smash the frame's leading byte
            yield bytes([rng.randrange(256)]) + base[1:]
        else:
            yield b""


def _fuzz_one(tag, decode, reencode, seeds):
    # Exactness on every canonical seed first.
    for seed in seeds:
        assert reencode(decode(seed)) == seed, f"{tag}: seed not canonical"
    escapes = []
    for frame in _mutations(tag, seeds, N_MUTATIONS):
        try:
            value = decode(frame)
        except TYPED_ERRORS:
            continue
        except Exception as e:  # noqa: BLE001 - the corpus contract
            escapes.append((frame[:40].hex(), repr(e)))
            continue
        # Survived decoding: must re-encode to a canonical fixpoint.
        e1 = reencode(value)
        e2 = reencode(decode(e1))
        assert e1 == e2, f"{tag}: decoded mutant is not canonical"
    assert not escapes, f"{tag}: decoder crashes escaped: {escapes[:5]}"


# ------------------------------------------------------------------ tests


def test_registry_closure():
    """Every registered codec tag has a fuzz sample; every sample names
    a registered tag. A tag in neither table is untested attack
    surface — add the SAMPLES entry with the registration, not later."""
    # Force the registries that populate on module import.
    import hyperdrive_tpu.harness.sim  # noqa: F401
    import hyperdrive_tpu.overlay.runtime  # noqa: F401
    import hyperdrive_tpu.transport  # noqa: F401

    registered = set(WIRE_CODECS) | set(WIRE_BUDGETS)
    known = set(SAMPLES) | {"overlay.partial"}  # object seam: no bytes
    missing = registered - known
    assert not missing, f"registered codecs without fuzz samples: {missing}"
    stale = known - registered
    assert not stale, f"fuzz samples for unregistered tags: {stale}"
    for tag in registered:
        assert wire_budget_for(tag) is not None, tag


@pytest.mark.parametrize("tag", sorted(
    t for t, row in SAMPLES.items() if row[0] is not None
))
def test_codec_fuzz(tag):
    decode, reencode, seeds = SAMPLES[tag]
    _fuzz_one(tag, decode, reencode, seeds)


def test_codec_fuzz_process_checkpoint():
    _fuzz_one("process.checkpoint", *_process_sample())


def test_codec_fuzz_scenario():
    _fuzz_one("scenario.record", *_scenario_sample())


def test_codec_fuzz_flight(tmp_path):
    decode, reencode, seeds = _flight_sample(tmp_path)
    for seed in seeds:
        assert reencode(decode(seed)) == seed
    for frame in _mutations("flight.record", seeds, N_MUTATIONS):
        try:
            msgs = decode(frame)
        except TYPED_ERRORS:
            continue
        # Flight logs tolerate truncation by contract (a partial
        # trailing frame = the recorder was killed mid-write): the
        # decoded prefix must itself be a canonical log.
        e1 = reencode(msgs)
        assert reencode(decode(e1)) == e1


def test_unregistered_tag_is_a_sanitizer_error(monkeypatch):
    monkeypatch.setenv("HD_SANITIZE", "1")
    with pytest.raises(SanitizerError, match="HDS005"):
        maybe_wire_reader("no.such.codec", b"\x00")


# ------------------------------------------------- pinned decode fixes


def test_envelope_rejects_oversized_signature():
    """unmarshal_message caps the detached signature (HD008 fix): a
    peer cannot ride megabytes of junk behind a valid vote."""
    w = Writer()
    w.i8(int(MessageType.PREVOTE))
    _prevote().marshal(w)
    w.raw(b"\x00" * 4096)
    with pytest.raises(SerdeError, match="signature too wide"):
        unmarshal_message(maybe_wire_reader("msg.envelope", w.data()))


def test_request_rejects_trailing_garbage():
    """decode_request rejects a frame with bytes after the request body
    (typed, never silently half-decoded). A hello's last 8 bytes are
    the optional t0 echo stamp, so its garbage lands AFTER a stamped
    frame; a partial (non-f64-sized) hello tail is a typed short read."""
    pad = Writer()
    pad.u32(0)
    for frame in (encode_query(9, 5),
                  encode_hello("t", [], 0, t0=1.5),
                  encode_submit(9, 7, 2, b"\x11" * 32, []),
                  encode_metrics_request(9)):
        with pytest.raises(SerdeError, match="trailing bytes"):
            decode_request(frame + pad.data())
    with pytest.raises(SerdeError):
        decode_request(encode_hello("t", [], 0) + pad.data())


def test_request_rejects_oversized_name_and_row_sig():
    with pytest.raises(SerdeError, match="name too long"):
        decode_request(encode_hello("x" * 300, [], 0))
    with pytest.raises(SerdeError, match="signature too wide"):
        decode_request(encode_submit(
            9, 7, 2, b"\x11" * 32, [(b"\x22" * 32, b"\x00" * 200)]
        ))


def test_result_rejects_noncanonical_bitmap():
    """The result bitmap must be exactly ceil(n/8) bytes — wider is as
    malformed as narrower."""
    w = Writer()
    w.u8(3)  # TAG_RESULT
    w.u64(9)
    w.u8(STATUS_COMMITTED)
    w.u32(3)
    w.raw(b"\x05\x00")  # 2 bytes for n=3; canonical is 1
    w.raw(b"")  # root
    w.raw(b"")  # cert
    with pytest.raises(SerdeError, match="bitmap width"):
        decode_result(w.data())
    # ... and the canonical frame still decodes.
    ok = encode_result(9, STATUS_COMMITTED, 3, [True, False, True])
    assert decode_result(ok)[2] == [True, False, True]


def test_proof_rejects_trailing_garbage():
    with pytest.raises(SerdeError, match="trailing bytes"):
        decode_proof(encode_proof(9, STATUS_NO_QUORUM) + b"\x00")


def test_overlay_rejects_wide_mask_and_extras_flood():
    """on_frame's Byzantine shape caps: a mask wider than the committee
    or an extras flood is counted, scored, and dropped before any state
    grows — never merged, never a crash."""
    from hyperdrive_tpu.harness.sim import Simulation
    from hyperdrive_tpu.overlay import OverlayConfig, OverlayFrame

    sim = Simulation(n=8, seed=5, target_height=1, delivery_cost=1e-3,
                     overlay=OverlayConfig())
    sim.run(max_steps=50_000)
    rt = sim._overlay
    assert rt.frame_rejects == 0  # honest runs never trip the caps
    slot = next(iter(rt._slots))
    invalid = rt.scores.charges["invalid"]
    rt.on_frame(1, OverlayFrame(2, slot, 0, mask=1 << (rt.n + 40)))
    assert rt.frame_rejects == 1
    rt.on_frame(1, OverlayFrame(
        2, slot, 0, mask=0,
        extras=tuple(_prevote() for _ in range(rt.n + 1)),
    ))
    assert rt.frame_rejects == 2
    assert rt.scores.charges["invalid"] == invalid + 2
