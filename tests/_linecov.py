"""Stdlib line-coverage measurement via sys.monitoring (PEP 669).

The build image has no pytest-cov (and installs are off), but the CI
coverage gate must be pinned at a MEASURED number, not a floor. This
plugin measures statement coverage of ``hyperdrive_tpu/`` with the
Python 3.12 monitoring API at near-zero overhead — every line callback
DISABLEs its own location after the first hit, so steady-state cost is
one dict probe per never-seen line. Enable with ``HD_LINECOV=1``; the
report prints one summary line and writes the per-file breakdown to
``HD_LINECOV_OUT`` (default ``.linecov.partial.json`` at the repo root
— set ``HD_LINECOV_OUT=linecov.json`` on a FULL-suite run to refresh
the published artifact; partial runs must not clobber it).

Methodology vs coverage.py: executable lines are the union of
``co_lines()`` over every code object compiled from each module.
Docstring/annotation-only lines are attributed slightly differently
than coverage.py's AST analysis, and subprocess children (the transport
and multihost workers) are not traced — both hold for a default
pytest-cov run too, but the absolute number can still differ by a point
or two, so the CI gate carries a small allowance below the number
measured here (see .github/workflows/ci.yml).
"""

from __future__ import annotations

import json
import os
import sys

_TOOL = sys.monitoring.COVERAGE_ID
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "hyperdrive_tpu") + os.sep
_hits: dict[str, set[int]] = {}
_engaged = False


def _on_line(code, line):
    f = code.co_filename
    if f.startswith(_PKG):
        _hits.setdefault(f, set()).add(line)
    return sys.monitoring.DISABLE


def start() -> None:
    global _engaged
    try:
        sys.monitoring.use_tool_id(_TOOL, "hd-linecov")
    except ValueError:
        # Another coverage tool owns the slot (e.g. coverage.py with
        # COVERAGE_CORE=sysmon); defer to it. report() then refuses to
        # publish — an all-zero artifact would masquerade as a
        # measurement.
        return
    sys.monitoring.register_callback(
        _TOOL, sys.monitoring.events.LINE, _on_line
    )
    sys.monitoring.set_events(_TOOL, sys.monitoring.events.LINE)
    _engaged = True


def _exec_lines(path: str) -> set[int]:
    """Executable line numbers: co_lines() of every code object the
    module compiles to (functions, comprehensions, class bodies)."""
    with open(path, "rb") as f:
        src = f.read()
    lines: set[int] = set()
    code_t = type(_exec_lines.__code__)
    stack = [compile(src, path, "exec")]
    while stack:
        co = stack.pop()
        for _, _, ln in co.co_lines():
            if ln:
                lines.add(ln)
        for c in co.co_consts:
            if isinstance(c, code_t):
                stack.append(c)
    return lines


_report_cache: dict | None = None


def report(write=print) -> "dict | None":
    global _report_cache
    if not _engaged:
        write("HD_LINECOV: not engaged (monitoring slot owned by "
              "another tool) — no measurement published")
        return None
    if _report_cache is not None:
        return _report_cache
    per_file = {}
    tot_exec = tot_hit = 0
    for root, _dirs, files in os.walk(_PKG):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            ex = _exec_lines(path)
            hit = _hits.get(path, set()) & ex
            tot_exec += len(ex)
            tot_hit += len(hit)
            rel = os.path.relpath(path, _REPO)
            per_file[rel] = {
                "exec": len(ex),
                "hit": len(hit),
                "pct": round(100 * len(hit) / len(ex), 1) if ex else 100.0,
                "missing": sorted(ex - hit)[:200],
            }
    pct = round(100 * tot_hit / tot_exec, 2) if tot_exec else 100.0
    out = {"total_pct": pct, "hit": tot_hit, "exec": tot_exec,
           "files": per_file}
    # The repo-root linecov.json is the PUBLISHED full-suite artifact
    # (cited by README and ci.yml); partial runs — gate smokes, single
    # test files — must not clobber it. Default the output elsewhere and
    # let the full-suite measurement opt in explicitly.
    path = os.environ.get(
        "HD_LINECOV_OUT", os.path.join(_REPO, ".linecov.partial.json")
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    write(
        f"HD_LINECOV total: {pct}% ({tot_hit}/{tot_exec} lines) "
        f"-> {os.path.basename(path)}"
    )
    _report_cache = out
    return out


def gate_ok(write=print) -> bool:
    """The coverage GATE: measured total vs the HD_LINECOV_MIN env
    threshold (same tool that produced the published number, so the
    gate's unit is exactly the measurement's — no cross-tool
    allowance). True when no threshold is set, measurement never
    engaged, or the total meets it."""
    min_pct = float(os.environ.get("HD_LINECOV_MIN", "0") or 0)
    if not min_pct:
        return True
    if not _engaged:
        # Threshold explicitly set but the measurement never engaged
        # (another tool owns the monitoring slot): fail LOUDLY — a
        # silently no-op'd gate would let real regressions merge green.
        write(
            "HD_LINECOV GATE FAILED: HD_LINECOV_MIN is set but the "
            "monitoring slot was unavailable (another coverage tool owns "
            "it) — no measurement was taken"
        )
        return False
    out = report(write)
    if out is None:
        return False
    ok = out["total_pct"] >= min_pct
    if not ok:
        write(
            f"HD_LINECOV GATE FAILED: {out['total_pct']}% < "
            f"{min_pct}% (HD_LINECOV_MIN)"
        )
    return ok
