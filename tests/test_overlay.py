"""Byzantine-resilient aggregation overlay (ISSUE 12).

The dissemination layer between replica and harness
(hyperdrive_tpu/overlay/): seeded binomial aggregation tree, partial-
aggregate frames scored by new-signer coverage, windowed level
escalation with a ranked never-starve fallback, and device-batched
partial verification. The contract under test, in rough order of
importance:

- the tree is a PURE function of (seed, epoch anchor, validator set) —
  identical across instances, processes, and replay-from-dump;
- the overlay changes the transport, never the agreed values: commit
  digests are byte-identical to the all-to-all baseline, with and
  without Byzantine contributors;
- contribution scoring demotes misbehaving contributors and NEVER
  leaves an honest peer demoted once faults heal (rehabilitation +
  contribution credit);
- replay needs no overlay wiring at all — records hold plain
  per-message deliveries (frames/ticks are never recorded).
"""

import subprocess
import sys

import pytest

from hyperdrive_tpu.chaos.monitor import InvariantMonitor
from hyperdrive_tpu.chaos.plan import FaultPlan
from hyperdrive_tpu.epochs import EpochConfig, genesis_anchor
from hyperdrive_tpu.harness.sim import Simulation
from hyperdrive_tpu.overlay import (
    CHARGE_WEIGHTS,
    ContributionScores,
    OverlayConfig,
    OverlayFaults,
    Topology,
)


def _identities(seed, n):
    import hashlib

    return [
        hashlib.sha256(b"sim-replica-%d-%d" % (seed, i)).digest()
        for i in range(n)
    ]


# ---------------------------------------------------------------- topology


def test_topology_is_pure_function_of_seed_anchor_and_set():
    # Satellite: same (seed, anchor, validator set) -> same tree, down
    # to the digest; any input differing -> a different permutation.
    ids = _identities(3, 12)
    a = Topology(3, genesis_anchor(3), ids)
    b = Topology(3, genesis_anchor(3), list(ids))
    assert a.digest() == b.digest()
    assert a.rank == b.rank
    assert Topology(4, genesis_anchor(3), ids).digest() != a.digest()
    assert Topology(3, genesis_anchor(4), ids).digest() != a.digest()
    assert (
        Topology(3, genesis_anchor(3), ids[:-1]).digest() != a.digest()
    )


def test_topology_identical_across_processes():
    # The digest must not depend on anything process-local (hash
    # randomization, dict order, id()): recompute it in a fresh
    # interpreter and compare byte-for-byte.
    ids = _identities(7, 9)
    local = Topology(7, genesis_anchor(7), ids).digest().hex()
    code = (
        "from hyperdrive_tpu.epochs import genesis_anchor\n"
        "from hyperdrive_tpu.overlay import Topology\n"
        "import hashlib\n"
        "ids=[hashlib.sha256(b'sim-replica-%d-%d'%(7,i)).digest() "
        "for i in range(9)]\n"
        "print(Topology(7, genesis_anchor(7), ids).digest().hex())\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        check=True,
    )
    assert out.stdout.strip() == local


def test_topology_structure_invariants():
    # Ranks are a permutation of the padded space's first n entries;
    # partner halves are disjoint sibling blocks; level_groups(l)
    # tiles the rank space into 2**l-rank blocks.
    for n in (1, 2, 5, 8, 13, 16):
        t = Topology(11, genesis_anchor(11), _identities(11, n))
        assert sorted(t.rank) == sorted(
            set(t.rank)
        ), "ranks must be distinct"
        assert t.size >= n and t.size == 1 << max(0, t.levels)
        for lvl in range(1, t.levels + 1):
            groups = t.level_groups(lvl)
            seen = set()
            for g in groups:
                assert not (seen & set(g))
                seen |= set(g)
            assert seen == set(range(n))


def test_topology_contacts_prefix_stable():
    # contacts(slot, level, k) is a lazily-extended seeded shuffle:
    # asking for more contacts extends the list without reordering the
    # prefix already issued (wave w's contacts never change when wave
    # w+1 draws).
    t = Topology(5, genesis_anchor(5), _identities(5, 16))
    short = list(t.contacts(0, 3, 2))
    longer = list(t.contacts(0, 3, 6))
    assert longer[:2] == short


# ----------------------------------------------------------------- scoring


def test_scores_charge_demote_recover_cycle():
    events = []
    s = ContributionScores(
        4,
        on_demote=lambda p, sc, cls: events.append(("demote", p, cls)),
        on_recover=lambda p, sc: events.append(("recover", p)),
    )
    for _ in range(2):
        s.charge(1, "invalid")  # 6 each
    assert s.is_demoted(1)
    assert events[0] == ("demote", 1, "invalid")
    # Demotion is advisory: peer 1 ranks last but is still present.
    assert s.ranked()[-1] == 1
    s.credit_coverage(1, 3)  # +6: -12 -> -6 > demote_at
    assert not s.is_demoted(1)
    assert ("recover", 1) in events
    assert s.charges["invalid"] == 2


def test_scores_clamp_at_floor_and_weights_match_vocabulary():
    s = ContributionScores(2, floor=-10)
    for _ in range(50):
        s.charge(0, "invalid")
    assert s.scores[0] == -10
    assert set(CHARGE_WEIGHTS) == {
        "invalid",
        "stale_generation",
        "duplicate",
        "withheld",
    }


def test_scores_rehabilitate_pulls_toward_zero_and_recovers():
    s = ContributionScores(3)
    for _ in range(4):
        s.charge(2, "invalid")  # -24, demoted
    s.credit_coverage(0, 5)  # +10
    assert s.is_demoted(2)
    s.rehabilitate(10)
    assert s.scores[2] == -14 and s.is_demoted(2)
    s.rehabilitate(10)
    assert s.scores[2] == -4 and not s.is_demoted(2)
    # Positive scores decay toward zero too (windowed reputation), and
    # zero is a fixed point.
    assert s.scores[0] == 0
    s.rehabilitate(10)
    assert s.scores[0] == 0


# -------------------------------------------------------------- validation


def test_config_and_fault_validation_errors():
    with pytest.raises(ValueError):
        OverlayConfig(fanout=0).validate(8)
    with pytest.raises(ValueError):
        OverlayConfig(max_waves=0).validate(8)
    with pytest.raises(ValueError):
        OverlayConfig(level_window=0.0).validate(8)
    with pytest.raises(ValueError):
        OverlayConfig(heal_rate=-1).validate(8)
    with pytest.raises(ValueError):
        OverlayFaults(byzantine=(0, 1, 2)).validate(8)  # > f
    with pytest.raises(ValueError):
        OverlayFaults(byzantine=(9,)).validate(8)
    with pytest.raises(ValueError):
        OverlayFaults(garbage_rate=1.5).validate(8)
    with pytest.raises(ValueError):
        Simulation(
            n=8, target_height=2, overlay=OverlayConfig(),
            delivery_cost=0.0,
        )
    with pytest.raises(ValueError):
        Simulation(
            n=8,
            target_height=2,
            overlay=OverlayConfig(),
            delivery_cost=1e-3,
            drop_rate=0.1,
        )
    with pytest.raises(ValueError):
        Simulation(
            n=8, target_height=2, overlay=OverlayConfig(),
            delivery_cost=1e-3, burst=True,
        )


# ----------------------------------------------------- digest neutrality


@pytest.mark.parametrize("n", [4, 8, 16])
def test_overlay_commits_identical_to_all_to_all(n):
    # The tentpole's core safety claim: aggregation changes the
    # transport, never the agreed values. Same seed, same chain,
    # byte-for-byte, at every committee size.
    base = Simulation(n=n, seed=23, target_height=4, delivery_cost=1e-3)
    bres = base.run()
    ov = Simulation(
        n=n,
        seed=23,
        target_height=4,
        delivery_cost=1e-3,
        overlay=OverlayConfig(),
    )
    ores = ov.run()
    assert bres.completed and ores.completed
    assert ores.commit_digest(up_to=4) == bres.commit_digest(up_to=4)
    snap = ov.overlay_snapshot()
    assert snap["frames"] > 0
    assert snap["scores"]["demoted"] == []


def test_overlay_neutral_under_byzantine_contributors():
    # Byzantine contributors garble/withhold partial aggregates; the
    # chain must still byte-match the clean all-to-all baseline (the
    # invalid rows are isolated and charged, never delivered).
    base = Simulation(n=16, seed=31, target_height=4, delivery_cost=1e-3)
    bres = base.run()
    faults = OverlayFaults(
        byzantine=(2, 9), withhold_levels=(1,), garbage_rate=0.5
    )
    ov = Simulation(
        n=16,
        seed=31,
        target_height=4,
        delivery_cost=1e-3,
        overlay=OverlayConfig(faults=faults),
    )
    ores = ov.run()
    assert ores.completed
    assert ores.commit_digest(up_to=4) == bres.commit_digest(up_to=4)
    snap = ov.overlay_snapshot()
    assert snap["frames_garbage"] > 0
    assert set(snap["scores"]["demoted"]) <= {2, 9}
    assert snap["honest_demoted"] == []


def test_overlay_replay_from_dump_needs_no_overlay_wiring():
    # Records hold plain (to, vote) deliveries — frames and ticks are
    # never recorded — so a dump replays with NO overlay kwargs and
    # reproduces the exact commits. This is what makes overlay dumps
    # debuggable by the standard chaos replay CLI.
    sim = Simulation(
        n=8,
        seed=45,
        target_height=4,
        delivery_cost=1e-3,
        overlay=OverlayConfig(
            faults=OverlayFaults(byzantine=(5,), garbage_rate=0.4)
        ),
    )
    res = sim.run()
    assert res.completed
    replayed = Simulation.replay(sim.record)
    assert replayed.commits == res.commits


def test_overlay_coalesced_ingest_differential():
    # coalesce_ingest batches a frame's constituents through
    # handle_coalesced instead of per-message handle; the chain must
    # not move.
    a = Simulation(
        n=8,
        seed=52,
        target_height=4,
        delivery_cost=1e-3,
        overlay=OverlayConfig(),
    )
    ra = a.run()
    b = Simulation(
        n=8,
        seed=52,
        target_height=4,
        delivery_cost=1e-3,
        overlay=OverlayConfig(coalesce_ingest=True),
    )
    rb = b.run()
    assert ra.completed and rb.completed
    assert rb.commit_digest(up_to=4) == ra.commit_digest(up_to=4)


def test_signed_overlay_verifies_each_vote_exactly_once():
    # Verification dedup: the overlay device-verifies each vote ONCE
    # network-wide (first forwarding frame pays it, batched per level
    # through the DeviceWorkQueue) plus one row per propose — against
    # n * (n-1) * votes for all-to-all host verification.
    n, h = 8, 3
    base = Simulation(
        n=n, seed=61, target_height=h, delivery_cost=1e-3, sign=True
    )
    bres = base.run()
    ov = Simulation(
        n=n,
        seed=61,
        target_height=h,
        delivery_cost=1e-3,
        sign=True,
        overlay=OverlayConfig(),
    )
    ores = ov.run()
    assert bres.completed and ores.completed
    assert ores.commit_digest(up_to=h) == bres.commit_digest(up_to=h)
    snap = ov.overlay_snapshot()
    # Exactly once per (vote in table) + once per propose; the precise
    # count varies with round traffic, but the once-per-vote bound is
    # what kills the O(n^2) verify bill.
    assert 0 < snap["verify_rows"] <= 2 * n * (h + 1) + n


# ------------------------------------------------------------ epochs/chaos


def test_overlay_rekeys_at_epoch_boundaries():
    # Churn re-keys tree positions: the topology digest must change at
    # every boundary (new anchor + rotated set), and the chain must
    # match the same epoch schedule run WITHOUT the overlay.
    epochs = EpochConfig(epoch_length=2, committee_size=8,
                         rekey_per_epoch=2)
    base = Simulation(
        n=8,
        seed=77,
        target_height=6,
        delivery_cost=1e-3,
        epochs=epochs,
    )
    bres = base.run()
    ov = Simulation(
        n=8,
        seed=77,
        target_height=6,
        delivery_cost=1e-3,
        epochs=epochs,
        overlay=OverlayConfig(),
    )
    ores = ov.run()
    assert bres.completed and ores.completed
    assert ores.commit_digest(up_to=6) == bres.commit_digest(up_to=6)
    snap = ov.overlay_snapshot()
    assert snap["rekeys"] >= 2
    assert ov.epoch >= 2


def test_overlay_requires_full_committee_with_epochs():
    with pytest.raises(ValueError):
        Simulation(
            n=8,
            target_height=4,
            delivery_cost=1e-3,
            epochs=EpochConfig(epoch_length=2, committee_size=6),
            overlay=OverlayConfig(),
        )


def test_fault_plan_overlay_family_is_deterministic():
    p1, f1 = FaultPlan.overlay(9, 16)
    p2, f2 = FaultPlan.overlay(9, 16)
    assert p1 == p2 and f1 == f2
    assert f1.byzantine and len(f1.byzantine) <= 16 // 3
    # The tree-slicing partition isolates a level block disjoint from
    # the Byzantine set (the two stressors compose, not shadow).
    if p1.partitions:
        assert not (set(p1.partitions[0].groups[0]) & set(f1.byzantine))


def test_overlay_chaos_honest_peers_recover_after_heal():
    # The acceptance run: tree-slicing partition + Byzantine
    # contributors + interior crash, monitor armed. No honest peer may
    # finish demoted (rehabilitation + contribution credit must refill
    # the partition-window charges), never-starve must hold, and the
    # record must replay without overlay wiring.
    plan, faults = FaultPlan.overlay(19951, 8)
    sim = Simulation(
        n=8,
        seed=19951,
        target_height=8,
        timeout=1.0,
        delivery_cost=1e-3,
        chaos=plan,
        observe=True,
        overlay=OverlayConfig(faults=faults),
    )
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=500_000)
    monitor.check_final(result)  # includes _check_overlay
    snap = sim.overlay_snapshot()
    assert snap["honest_demoted"] == []
    assert snap["scores"]["demotions"] > 0  # faults actually bit
    replayed = Simulation.replay(sim.record)
    assert replayed.commits == result.commits


def test_overlay_report_decoder_round_trip(tmp_path):
    # obs report --overlay: the journal alone must reconstruct frame
    # flow, charges, escalations, and demotions (OBSERVABILITY.md).
    from hyperdrive_tpu.obs.report import (
        overlay_summary,
        render_overlay_table,
    )

    sim = Simulation(
        n=8,
        seed=88,
        target_height=3,
        delivery_cost=1e-3,
        observe=True,
        overlay=OverlayConfig(
            faults=OverlayFaults(byzantine=(3,), garbage_rate=0.6)
        ),
    )
    res = sim.run()
    assert res.completed
    summary = overlay_summary(sim.obs.snapshot())
    snap = sim.overlay_snapshot()
    assert summary["frames"] > 0
    assert summary["charges"]["invalid"] == (
        snap["scores"]["charges"]["invalid"]
    )
    assert summary["still_demoted"] == snap["scores"]["demoted"]
    text = render_overlay_table(summary)
    assert "frames" in text and "level" in text


# ------------------------------------------------------------ BLS partials


def test_overlay_bls_partials_digest_neutral():
    # Real BLS partial aggregates on every frame (host fold) must not
    # bend the agreed chain, and a clean run never fires the merge check.
    base = Simulation(n=4, target_height=3, seed=11, timeout=1.0,
                      delivery_cost=1e-3)
    bres = base.run(max_steps=200_000)
    sim = Simulation(n=4, target_height=3, seed=11, timeout=1.0,
                     delivery_cost=1e-3,
                     overlay=OverlayConfig(bls_partials=True))
    sres = sim.run(max_steps=200_000)
    assert (sres.commit_digest(up_to=3) == bres.commit_digest(up_to=3))
    snap = sim.overlay_snapshot()
    assert snap["bls_partials"] is True
    assert snap["bls_partials_attached"] > 0
    assert snap["bls_partial_rejects"] == 0


def test_overlay_bls_corrupted_aggregate_charged_at_merge():
    # Byzantine garblers on a BLS run send frames claiming their REAL
    # coverage under a corrupted aggregate: every one must be caught by
    # the receiver's recomputed masked sum — before any coverage merge
    # or batch verify — and charged to the contributor. A deterministic
    # probe then replays a real frame with one flipped aggregate byte.
    from hyperdrive_tpu.overlay import OverlayFrame

    plan, faults = FaultPlan.overlay(11, 8)
    sim = Simulation(n=8, target_height=3, seed=11, timeout=1.0,
                     delivery_cost=1e-3, chaos=plan, observe=True,
                     overlay=OverlayConfig(faults=faults,
                                           bls_partials=True))
    mon = InvariantMonitor(sim)
    res = sim.run(max_steps=200_000)
    mon.check_final(res)
    rt = sim._overlay
    assert rt.bls_partial_rejects > 0  # organic garbled-agg detections
    slot, st = next((sl, s) for sl, s in rt._slots.items() if s.bls)
    mask = st.all_mask
    good = rt._bls_masked_sum(st, mask, 0, 0)
    bad = bytes([good[0] ^ 0x01]) + good[1:]
    to = next((i for i in range(1, 8) if mask & ~st.cov[i]), 1)
    cov, rejects = st.cov[to], rt.bls_partial_rejects
    invalid = rt.scores.charges["invalid"]
    rt.on_frame(to, OverlayFrame(0, slot, 0, mask, agg=bad))
    assert rt.bls_partial_rejects == rejects + 1
    assert rt.scores.charges["invalid"] == invalid + 1
    assert st.cov[to] == cov  # nothing merged from the poisoned frame
    if mask & ~cov:
        rt.on_frame(to, OverlayFrame(0, slot, 0, mask, agg=good))
        assert st.cov[to] != cov  # the honest retry merges fine


@pytest.mark.slow  # compiles the vmapped G1 aggregation kernel
def test_overlay_bls_device_launcher_matches_host_fold():
    # Same seed, same faults-free overlay: partial-aggregate merges
    # batched through the DeviceWorkQueue's G1SumLauncher must commit
    # the identical chain the host fold commits, and actually launch.
    from hyperdrive_tpu.devsched.queue import DeviceWorkQueue

    host = Simulation(n=4, target_height=2, seed=11, timeout=1.0,
                      delivery_cost=1e-3,
                      overlay=OverlayConfig(bls_partials=True))
    hres = host.run(max_steps=200_000)
    queue = DeviceWorkQueue()
    dev = Simulation(n=4, target_height=2, seed=11, timeout=1.0,
                     delivery_cost=1e-3, devsched=queue,
                     overlay=OverlayConfig(bls_partials=True))
    dres = dev.run(max_steps=200_000)
    assert dres.commit_digest() == hres.commit_digest()
    assert dev._overlay._bls_launcher is not None
    assert dev._overlay._bls_launcher.launched > 0
