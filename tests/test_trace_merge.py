"""Distributed tracing: stamp codec, journal merge determinism,
critical-path attribution, SLO evaluation, and the merged Perfetto
export. All jax-free — the trace plane is pure stdlib by design.

The determinism contract mirrors the recorder's own: fixed inputs →
byte-identical merged journals (``merged_digest``) and identical
critical-path tables. Virtual-clock journals carry no ``trace.offset``
events, so their merge is a pure deterministic interleave; wall-clock
merges align on the HELLO echo estimates but the causal clamp keeps
``trace.recv`` from ever preceding its ``trace.send``.
"""

from __future__ import annotations

import json

import pytest

from hyperdrive_tpu.codec import SerdeError
from hyperdrive_tpu.obs.merge import (
    estimate_offsets,
    merge_journals,
    merged_digest,
    save_merged,
)
from hyperdrive_tpu.obs.perfetto import to_trace_events
from hyperdrive_tpu.obs.recorder import Event, Recorder, load_journal
from hyperdrive_tpu.obs.report import (
    critical_path_summary,
    render_critical_path_table,
)
from hyperdrive_tpu.obs.slo import evaluate_slos
from hyperdrive_tpu.obs.tracectx import (
    STAMP_LEN,
    TRACE_MAGIC,
    TraceSource,
    decode_stamp,
    encode_stamp,
    span_id,
    split_frame,
)


# ------------------------------------------------------------ stamp codec


def test_stamp_roundtrip_and_length():
    frame = encode_stamp(7, 1234, span_id(3, 9))
    assert len(frame) == STAMP_LEN
    assert frame[0] == TRACE_MAGIC
    assert decode_stamp(frame) == (7, 1234, (3 << 32) | 9)


def test_stamp_rejects_bad_magic_and_trailing():
    frame = encode_stamp(1, 1)
    with pytest.raises(SerdeError):
        decode_stamp(b"\x00" + frame[1:])
    with pytest.raises(SerdeError):
        decode_stamp(frame + b"\x00")


def test_split_frame_passthrough_for_unstamped():
    # Consensus envelopes open with a small i8 tag, service frames with
    # 1..5 — none collide with the magic, so unstamped frames pass
    # through byte-identically (the interop guarantee).
    for payload in (b"\x01rest-of-frame", b"\x05xyz", b""):
        ctx, rest = split_frame(payload)
        assert ctx is None and rest == payload
    stamped = encode_stamp(2, 5) + b"\x01rest"
    ctx, rest = split_frame(stamped)
    assert ctx == (2, 5, 0) and rest == b"\x01rest"


def test_trace_source_monotone_and_emitting():
    rec = Recorder()
    src = TraceSource(4, obs=rec.scoped(-1))
    out = src.stamp(b"payload", height=7)
    assert split_frame(out) == ((4, 1, 0), b"payload")
    src.stamp(b"x")
    kinds = [(ev[4], ev[5]) for ev in rec.snapshot()]
    assert kinds == [("trace.send", "4:1"), ("trace.send", "4:2")]
    with pytest.raises(ValueError):
        TraceSource(0)


# ---------------------------------------------------- synthetic journals


def _journal(origin, events, **extra):
    data = {
        "version": 1,
        "capacity": 65536,
        "total": len(events),
        "dropped": 0,
        "events": [list(ev) for ev in events],
        "meta": {"origin": origin},
    }
    data.update(extra)
    return data


def _two_process_run(skew=0.0, drop_sender=False):
    """A hand-built 2-process exchange: the server (origin 1) commits
    height 1 after the client (origin 2) submits; the client's clock
    runs ``skew`` seconds ahead of the server's."""
    server = [
        (10.000, -1, 1, -1, "trace.recv", "2:1"),
        (10.001, -1, 1, 0, "service.remote.submit", 4),
        (10.003, -1, 1, 0, "cert.emit", None),
        (10.004, -1, 1, 0, "service.remote.resolve", "committed"),
        (10.005, -1, -1, -1, "trace.send", "1:1"),
    ]
    client = [
        (9.998 + skew, -1, -1, -1, "trace.send", "2:1"),
        # The echo handshake's estimate: server clock = client - skew.
        (9.999 + skew, -1, -1, -1, "trace.offset", f"1:{-skew:.6f}"),
        (10.006 + skew, -1, -1, -1, "trace.recv", "1:1"),
        (10.007 + skew, -1, 1, -1, "commit", None),
    ]
    if drop_sender:
        server = [ev for ev in server if ev[4] != "trace.send"]
    return [_journal(1, server), _journal(2, client)]


def test_merge_is_deterministic_and_digest_stable():
    a = merge_journals(_two_process_run())
    b = merge_journals(_two_process_run())
    assert merged_digest(a) == merged_digest(b)
    assert a["events"] == b["events"]
    assert a["meta"]["origins"] == [1, 2]
    assert a["meta"]["orphans"] == []
    # pid stamping: every merged event carries its origin in slot 7.
    pids = {Event(tuple(ev)).pid for ev in a["events"]}
    assert pids == {1, 2}


def test_merge_aligns_skewed_clocks():
    skewed = merge_journals(_two_process_run(skew=5.0))
    flat = merge_journals(_two_process_run(skew=0.0))
    # Offset estimation maps the skewed client back onto the server
    # clock, so the merged ORDER matches the zero-skew merge exactly.
    order = lambda m: [(ev[4], ev[6]) for ev in m["events"]]
    assert order(skewed) == order(flat)
    assert skewed["meta"]["offsets"]["2"] == pytest.approx(-5.0)


def test_merge_clamps_causality():
    # A wildly-wrong offset estimate cannot order a recv before its
    # send: detail-matched spans are clamped, so the server's recv of
    # "2:1" never precedes the client's send of "2:1".
    journals = _two_process_run(skew=5.0)
    # Corrupt the estimate: claim the clocks agree when they don't.
    journals[1]["events"] = [
        list(ev) if ev[4] != "trace.offset" else
        [ev[0], ev[1], ev[2], ev[3], ev[4], "1:5.0"]
        for ev in journals[1]["events"]
    ]
    merged = merge_journals(journals)
    by_kind = {}
    for ev in merged["events"]:
        if ev[4].startswith("trace.") and ev[5] == "2:1":
            by_kind[ev[4]] = ev[0]
    assert by_kind["trace.recv"] >= by_kind["trace.send"]


def test_merge_flags_orphans_never_drops():
    merged = merge_journals(_two_process_run(drop_sender=True))
    # The client's recv of "1:1" lost its sender — flagged, kept.
    assert merged["meta"]["orphans"] == ["2<-1:1"]
    kinds = [ev[4] for ev in merged["events"]]
    assert "trace.recv" in kinds  # the orphaned event is still there


def test_merge_rejects_duplicate_origins():
    j = _two_process_run()
    j[1]["meta"]["origin"] = 1
    with pytest.raises(ValueError, match="duplicate"):
        merge_journals(j)


def test_estimate_offsets_bfs_from_lowest_origin():
    journals = {
        1: [],
        2: [(0.0, -1, -1, -1, "trace.offset", "1:-3.0")],
        3: [(0.0, -1, -1, -1, "trace.offset", "2:1.0")],
    }
    deltas = estimate_offsets(journals)
    assert deltas[1] == 0.0  # the reference clock
    assert deltas[2] == pytest.approx(-3.0)
    assert deltas[3] == pytest.approx(-2.0)  # composed through 2


def test_merged_journal_roundtrips_through_load(tmp_path):
    merged = merge_journals(_two_process_run())
    path = tmp_path / "merged.json"
    save_merged(merged, path)
    loaded = load_journal(path)
    assert loaded["meta"]["merged"] is True
    assert [list(ev) for ev in loaded["events"]] == merged["events"]
    assert merged_digest(loaded) == merged_digest(merged)


# --------------------------------------------------------- critical path


def test_critical_path_attributes_every_hop():
    merged = merge_journals(_two_process_run())
    summary = critical_path_summary(merged["events"])
    assert len(summary["rows"]) == 1
    row = summary["rows"][0]
    assert row["height"] == 1
    # Full chain: send -> recv -> submit -> cert -> resolve -> commit.
    names = list(row["milestones"])
    assert names[0] == "send" and names[-1] == "commit"
    # Telescoping hops attribute exactly 100% of first-to-last span.
    assert row["attributed"] == pytest.approx(1.0)
    assert row["total_s"] == pytest.approx(
        sum(dt for _, dt in row["hops"])
    )
    assert summary["dominant"]  # some hop dominates
    table = render_critical_path_table(summary)
    assert "dominant hop" in table and "100%" in table


def test_critical_path_table_identical_across_merges():
    t1 = render_critical_path_table(
        critical_path_summary(merge_journals(_two_process_run())["events"])
    )
    t2 = render_critical_path_table(
        critical_path_summary(merge_journals(_two_process_run())["events"])
    )
    assert t1 == t2


# ------------------------------------------------------- perfetto export


def test_perfetto_merged_draws_cross_process_arrows():
    merged = merge_journals(_two_process_run())
    evs = to_trace_events([Event(tuple(ev)) for ev in merged["events"]])
    pids = {e["pid"] for e in evs if e["ph"] != "M"}
    assert pids == {1, 2}
    flows = [e for e in evs if e.get("cat") == "traceflow"]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], set()).add(e["pid"])
    # Both spans ("2:1" and "1:1") cross the process boundary.
    assert sum(1 for v in by_id.values() if len(v) > 1) == 2
    procs = [e for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(procs) == 2


def test_perfetto_single_process_journal_unchanged():
    # 6-tuple journals (no pid slot) still render under pid 0.
    evs = to_trace_events([
        (1.0, 0, 1, 0, "round.start", None),
        (2.0, 0, 1, 0, "commit", None),
    ])
    assert {e["pid"] for e in evs} == {0}


# ------------------------------------------------------------------- slo


def test_slo_evaluation_and_journal_marks():
    rec = Recorder()
    snapshot = {
        "counters": {}, "gauges": {},
        "histograms": {"tenant.commit.latency": {
            "t-a": {"count": 10, "sum": 1.0, "mean": 0.1,
                    "p50": 0.1, "p95": 0.2, "p99": 0.3},
        }},
    }
    events = [
        (1.0, -1, -1, -1, "service.remote.submit", 1),
        (2.0, -1, -1, -1, "service.remote.shed", "t-a"),
        (3.0, -1, -1, -1, "metrics.serve", 100),
        (4.0, -1, -1, -1, "metrics.shed", "t-a"),
    ]
    results = evaluate_slos(snapshot=snapshot, events=events,
                            obs=rec.scoped(-1))
    by_name = {r.name: r for r in results}
    assert by_name["finality_p99"].measured == pytest.approx(0.3)
    assert by_name["finality_p99"].ok  # 0.3 <= 0.75 ceiling
    assert by_name["shed_rate"].measured == pytest.approx(0.5)
    assert not by_name["shed_rate"].ok  # 0.5 > 0.25 ceiling
    assert "rollback_rate" not in by_name  # no speculation: skipped
    marks = {ev[4] for ev in rec.snapshot()}
    assert marks == {"slo.ok", "slo.breach"}


def test_slo_missing_inputs_are_skipped_not_passed():
    assert evaluate_slos() == []
    assert evaluate_slos(snapshot={"histograms": {}}) == []
