"""Messages: roundtrips, digests, equality, fuzz-no-panic.

Mirrors process/message_test.go's strategy: serde roundtrip equality,
digest stability/distinctness, and random-blob unmarshal must error rather
than crash.
"""

import pytest

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import (
    Precommit,
    Prevote,
    Propose,
    Timeout,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.testutil import random_precommit, random_prevote, random_propose
from hyperdrive_tpu.types import MessageType


def test_propose_roundtrip(rng):
    for _ in range(100):
        p = random_propose(rng)
        w = Writer()
        p.marshal(w)
        q = Propose.unmarshal(Reader(w.data()))
        assert p == q


def test_prevote_roundtrip(rng):
    for _ in range(100):
        p = random_prevote(rng)
        w = Writer()
        p.marshal(w)
        assert Prevote.unmarshal(Reader(w.data())) == p


def test_precommit_roundtrip(rng):
    for _ in range(100):
        p = random_precommit(rng)
        w = Writer()
        p.marshal(w)
        assert Precommit.unmarshal(Reader(w.data())) == p


def test_timeout_roundtrip():
    t = Timeout(message_type=MessageType.PREVOTE, height=7, round=3)
    w = Writer()
    t.marshal(w)
    assert Timeout.unmarshal(Reader(w.data())) == t


def test_tagged_roundtrip(rng):
    msgs = [random_propose(rng), random_prevote(rng), random_precommit(rng),
            Timeout(MessageType.PRECOMMIT, 1, 0)]
    for m in msgs:
        w = Writer()
        marshal_message(m, w)
        assert unmarshal_message(Reader(w.data())) == m


def test_digest_excludes_sender(rng):
    p = random_prevote(rng)
    q = Prevote(height=p.height, round=p.round, value=p.value, sender=rng.randbytes(32))
    assert p.digest() == q.digest()


def test_digest_domain_separation():
    pv = Prevote(height=1, round=0, value=b"\x01" * 32, sender=b"\x02" * 32)
    pc = Precommit(height=1, round=0, value=b"\x01" * 32, sender=b"\x02" * 32)
    assert pv.digest() != pc.digest()


def test_digest_sensitive_to_fields():
    base = Propose(height=1, round=0, valid_round=-1, value=b"\x01" * 32, sender=b"\x02" * 32)
    assert base.digest() != Propose(2, 0, -1, b"\x01" * 32, b"\x02" * 32).digest()
    assert base.digest() != Propose(1, 1, -1, b"\x01" * 32, b"\x02" * 32).digest()
    assert base.digest() != Propose(1, 0, 0, b"\x01" * 32, b"\x02" * 32).digest()
    assert base.digest() != Propose(1, 0, -1, b"\x03" * 32, b"\x02" * 32).digest()


def test_signature_excluded_from_equality(rng):
    p = random_prevote(rng)
    assert p == p.with_signature(b"\x01" * 64)


def test_unmarshal_fuzz_no_crash(rng):
    for _ in range(300):
        blob = rng.randbytes(rng.randint(0, 100))
        for cls in (Propose, Prevote, Precommit, Timeout):
            try:
                cls.unmarshal(Reader(blob))
            except SerdeError:
                pass
        try:
            unmarshal_message(Reader(blob))
        except SerdeError:
            pass


def test_int64_range_enforced_on_marshal():
    p = Propose(height=1 << 64, round=0, valid_round=-1,
                value=b"\x00" * 32, sender=b"\x00" * 32)
    with pytest.raises(SerdeError):
        w = Writer()
        p.marshal(w)
