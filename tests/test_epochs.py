"""Dynamic validator sets: election, epoch proofs, churn under chaos.

Unit layer: the stake-weighted proportional election (arXiv:2004.12990),
the EpochSchedule state machine (strict boundary order, idempotence,
fork detection, key rotation + retirement), and the epoch-proof wire
format + O(1)-per-hop chain verification.

Integration layer: full Simulation runs with ``epochs=EpochConfig(...)``
— record/replay determinism, stale-generation vote rejection,
checkpoint restore across an epoch boundary, the catchup-sweep rejoin
spec, and the 64-validator churn acceptance scenario (partition spanning
a boundary, crash-restore inside it, ~25% membership churn + one key
rotation per epoch).
"""

import dataclasses

import pytest

from hyperdrive_tpu.chaos.monitor import InvariantMonitor
from hyperdrive_tpu.chaos.plan import CrashRestart, FaultPlan, Partition
from hyperdrive_tpu.codec import Reader, Writer
from hyperdrive_tpu.epochs import (
    EpochChainError,
    EpochConfig,
    EpochSchedule,
    default_signatory,
    elect_committee,
    marshal_epoch_proof,
    set_digest,
    unmarshal_epoch_proof,
    verify_epoch_chain,
)
from hyperdrive_tpu.harness.sim import ScenarioRecord, Simulation
from hyperdrive_tpu.messages import Prevote

V = bytes(range(32))


# ----------------------------------------------------------------- election


def test_elect_committee_deterministic_distinct_sized():
    stakes = (3, 1, 4, 1, 5, 9, 2, 6)
    a = elect_committee(stakes, 5, b"material")
    b = elect_committee(stakes, 5, b"material")
    assert a == b
    assert len(a) == 5 and len(set(a)) == 5
    assert all(0 <= i < 8 for i in a)
    # Different material draws a different committee (overwhelmingly).
    assert elect_committee(stakes, 5, b"other") != a


def test_elect_committee_is_stake_proportional():
    # One validator holds ~90% of total stake: it must win a seat in
    # essentially every election. A uniform sampler would seat it in
    # only k/n of them.
    stakes = (100,) + (1,) * 11
    wins = sum(
        0 in elect_committee(stakes, 3, b"m%d" % i) for i in range(64)
    )
    assert wins >= 60
    # Zero-stake candidates are never seated.
    stakes = (0, 1, 1, 1)
    for i in range(16):
        assert 0 not in elect_committee(stakes, 3, b"z%d" % i)


def test_elect_committee_sybil_splitting_gains_no_expected_seats():
    # Adversarial stake splitting at the stake-floor boundary
    # (ROBUSTNESS.md "Adversarial economy"): an adversary holding total
    # stake S wins the same expected committee share whether it stands
    # as one account or splits into N floor-sized sybils — proportional
    # sampling weighs stake, not identities (arXiv:2004.12990). Seeded
    # multi-epoch property: anchors chain like EpochSchedule's.
    import hashlib

    honest = (10,) * 48
    k, epochs = 12, 192

    def seats(stakes, adversary_accounts):
        total, anchor = 0, b"sybil-split-genesis"
        for _ in range(epochs):
            anchor = hashlib.sha256(anchor).digest()
            committee = elect_committee(stakes, k, anchor)
            total += sum(i < adversary_accounts for i in committee)
        return total

    # Unsplit: one account holding 10 (a full honest validator's worth,
    # small enough that the one-seat-per-account cap never binds).
    unsplit = seats((10,) + honest, 1)
    # Split: ten sybils of 1 — each exactly at the floor, same total.
    split = seats((1,) * 10 + honest, 10)
    expected = epochs * k * 10 / 490.0  # ~47 over the campaign
    sigma = (epochs * k * (10 / 490.0)) ** 0.5
    assert abs(unsplit - expected) <= 4 * sigma
    assert abs(split - expected) <= 4 * sigma
    # The split trajectory gains nothing over the unsplit one beyond
    # sampling noise — splitting buys identities, never share.
    assert split - unsplit <= 4 * sigma
    # Splitting BELOW the floor forfeits everything: sub-floor stake
    # rounds to zero and zero-stake candidates are never seated.
    assert seats((0,) * 10 + honest, 10) == 0


def test_elect_committee_rejects_oversized():
    with pytest.raises(ValueError):
        elect_committee((1, 0, 1), 3, b"m")  # only 2 staked candidates


# ----------------------------------------------------------------- schedule


def test_schedule_boundaries_and_strict_order():
    sched = EpochSchedule((1,) * 8, 6, 2, 5)
    assert sched.epoch_of(1) == 0 and sched.epoch_of(2) == 0
    assert sched.epoch_of(3) == 1 and sched.epoch_of(4) == 1
    assert sched.is_boundary(2) and sched.is_boundary(4)
    assert not sched.is_boundary(1) and not sched.is_boundary(3)
    assert sched.boundary_height(0) == 2  # commit at 2 elects epoch 1
    assert sched.boundary_height(1) == 4
    with pytest.raises(ValueError):
        sched.transition_at(4, V)  # epoch 2's boundary before epoch 1's
    # Querying a committee that does not exist yet raises too.
    with pytest.raises(Exception):
        sched.signatories(1)


def test_schedule_rotation_retires_old_identity():
    sched = EpochSchedule((1,) * 8, 6, 2, 5, rekey_per_epoch=1)
    tr = sched.transition_at(2, V)
    assert tr.epoch == 1
    assert len(tr.committee) == 6 == len(tr.signatories)
    assert tr.set_digest == set_digest(tr.signatories)
    assert len(tr.rekeyed) == 1 == len(tr.retired)
    idx = tr.rekeyed[0]
    assert sched.generation_of(idx) == 1
    assert tr.retired[0] == default_signatory(idx, 0)
    assert default_signatory(idx, 1) not in tr.retired
    # Idempotent: the same boundary value returns the same transition.
    assert sched.transition_at(2, V).set_digest == tr.set_digest
    # Fork check: a different value at the same boundary is a safety
    # violation and must raise, not silently recompute.
    with pytest.raises(ValueError):
        sched.transition_at(2, bytes(32))


def test_schedule_committee_subset_of_pool():
    sched = EpochSchedule((1,) * 10, 7, 3, 9)
    for e, h in ((1, 3), (2, 6), (3, 9)):
        tr = sched.transition_at(h, bytes([e]) * 32)
        assert len(tr.signatories) == 7
        assert {v.index for v in tr.committee} <= set(range(10))
        assert sched.f(e) == 7 // 3
    assert sched.latest_epoch == 3


# -------------------------------------------------------------- epoch proofs


def _epoch_sim(n=8, target=8, seed=3, **kw):
    kw.setdefault(
        "epochs",
        EpochConfig(epoch_length=2, committee_size=6, rekey_per_epoch=1),
    )
    kw.setdefault("certificates", True)
    kw.setdefault("observe", True)
    return Simulation(n, target, seed=seed, **kw)


def _union_proofs(sim):
    covered = {}
    for c in sim.certifiers:
        for e, pr in c.proofs.items():
            covered.setdefault(e, pr)
    return [covered[e] for e in sorted(covered)]


def test_epoch_proof_chain_verifies_and_roundtrips():
    sim = _epoch_sim()
    res = sim.run()
    assert res.completed
    proofs = _union_proofs(sim)
    assert [p.epoch for p in proofs] == list(range(1, sim.epoch + 1))
    genesis = sim.epoch_schedule.signatories(0)
    assert verify_epoch_chain(genesis, proofs) == len(proofs)

    # Wire roundtrip: marshal -> unmarshal -> marshal is a fixed point
    # and the rehydrated chain still verifies.
    def blob(ps):
        w = Writer()
        for p in ps:
            marshal_epoch_proof(p, w)
        return w.data()

    r = Reader(blob(proofs))
    back = [unmarshal_epoch_proof(r) for _ in proofs]
    assert blob(back) == blob(proofs)
    assert verify_epoch_chain(genesis, back) == len(proofs)


def test_epoch_proof_chain_rejects_tampering():
    sim = _epoch_sim()
    sim.run()
    proofs = _union_proofs(sim)
    genesis = sim.epoch_schedule.signatories(0)
    # Tampered next-set digest: the certificate no longer commits to it.
    bad = list(proofs)
    bad[0] = dataclasses.replace(bad[0], next_set_digest=bytes(32))
    with pytest.raises(EpochChainError):
        verify_epoch_chain(genesis, bad)
    # A gap in the chain is not a verifiable chain.
    if len(proofs) >= 2:
        with pytest.raises(EpochChainError):
            verify_epoch_chain(genesis, [proofs[0], *proofs[2:]])
    # Wrong genesis: hop 1's certificate was signed by nobody we trust.
    with pytest.raises(EpochChainError):
        verify_epoch_chain([bytes(32)] * len(genesis), proofs)


# ---------------------------------------------------------- harness behavior


def test_epoch_sim_record_replays_identically(tmp_path):
    sim = _epoch_sim(seed=11)
    res = sim.run()
    assert res.completed and sim.epoch >= 3
    path = str(tmp_path / "epochs.bin")
    sim.record.dump(path)
    rec = ScenarioRecord.load(path)
    assert rec.epochs is not None
    replayed = Simulation.replay(rec, certificates=True)
    assert replayed.commits == res.commits
    assert replayed.completed


def test_stale_generation_vote_rejected():
    sim = _epoch_sim(seed=13)
    r = sim.replicas[0]
    old = sim.signatories[1]
    r.retired = {old: 3}
    # At or past the retirement bound: dropped, counted, never buffered.
    r.handle(Prevote(height=5, round=0, value=V, sender=old))
    assert r.stale_votes == 1
    r.handle(Prevote(height=7, round=0, value=V, sender=old))
    assert r.stale_votes == 2
    # Below the bound the old key is still valid — a laggard finishing
    # the boundary height keeps its quorum. No stale count.
    r.handle(Prevote(height=2, round=0, value=V, sender=old))
    assert r.stale_votes == 2
    kinds = [e.kind for e in sim.obs.snapshot()]
    assert kinds.count("epoch.stale_vote") == 2


def test_checkpoint_restore_across_epoch_boundary():
    # Crash a replica, keep it down long enough that the network crosses
    # at least one epoch boundary (election + key rotation) while only
    # its checkpoint survives; the restore path must re-apply epoch
    # state (rotated whoami, new committee whitelist) BEFORE rejoining,
    # and the run must stay fork- and equivocation-free.
    victim = 5
    plan = FaultPlan(
        crashes=(
            CrashRestart(
                replica=victim, crash_at_step=400, restart_after_steps=3000
            ),
        )
    )
    sim = _epoch_sim(seed=17, target=10, chaos=plan, delivery_cost=1e-3)
    mon = InvariantMonitor(sim)
    res = sim.run(max_steps=400_000)
    mon.check_final(res)
    assert mon.crashes and mon.restores
    # The network moved past epoch 1's boundary while the victim was
    # down: its restore resynced it beyond that boundary.
    assert mon.restores[0][1] > sim.epoch_schedule.boundary_height(0)
    r = sim.replicas[victim]
    assert r.proc.whoami == sim._identity[victim]
    assert not any(
        e.kind == "equivocation" for e in sim.obs.snapshot()
    ), "restored replica equivocated"


def test_catchup_sweep_bounds_rejoin_latency():
    # With heal-time resync disabled, the periodic laggard sweep is the
    # ONLY rejoin mechanism — so a tighter sweep cadence must strictly
    # bound how long an isolated replica stays behind, observable as
    # total steps to completion.
    def run(every):
        plan = FaultPlan(
            partitions=(
                Partition(
                    at=0.5,
                    heal=1.5,
                    groups=((3,),),
                    resync_on_heal=False,
                ),
            )
        )
        sim = Simulation(
            8,
            8,
            seed=23,
            delivery_cost=1e-3,
            chaos=plan,
            catchup_every=every,
        )
        res = sim.run(max_steps=400_000)
        assert res.completed
        return res.steps

    assert run(64) <= run(1024)


def test_catchup_params_validate():
    with pytest.raises(ValueError):
        Simulation(4, 2, seed=1, catchup_every=0)
    with pytest.raises(ValueError):
        Simulation(4, 2, seed=1, catchup_lag=-1)


# ------------------------------------------------------- acceptance scenario


def test_acceptance_64_validator_churn(tmp_path):
    # The ISSUE acceptance scenario: 64 validators, committee 48 (~25%
    # expected churn per election) + one key rotation per epoch, >= 3
    # epoch transitions, a partition spanning a boundary with a
    # crash-restore inside it. All honest replicas commit identical
    # digests, the union epoch-proof chain verifies end-to-end, the
    # invariant monitor stays silent, and the run replays exactly from
    # its own dumped record.
    n = 64
    plan = FaultPlan.churn(7, n, est_virtual_time=8.0)
    assert plan.partitions and plan.crashes
    sim = Simulation(
        n,
        13,
        seed=7,
        timeout=5.0,
        delivery_cost=1e-4,
        epochs=EpochConfig(
            epoch_length=4, committee_size=48, rekey_per_epoch=1
        ),
        certificates=True,
        observe=True,
        chaos=plan,
    )
    mon = InvariantMonitor(sim, max_rounds_after_heal=12)
    res = sim.run(max_steps=3_000_000)
    mon.check_final(res)  # fork/digest/liveness/epoch invariants
    assert res.completed
    assert sim.epoch >= 3 and len(mon.epoch_switches) >= 3
    assert mon.heals and mon.crashes and mon.restores
    assert sim._retired, "no key was ever rotated out"

    proofs = _union_proofs(sim)
    assert [p.epoch for p in proofs] == list(range(1, sim.epoch + 1))
    hops = verify_epoch_chain(sim.epoch_schedule.signatories(0), proofs)
    assert hops == sim.epoch

    path = str(tmp_path / "accept64.bin")
    sim.record.dump(path)
    replayed = Simulation.replay(ScenarioRecord.load(path))
    assert replayed.completed
    assert replayed.commits == res.commits
