"""Tests: tracing/metrics, logging helpers, and crash-restart checkpoints."""

import logging
import os

import pytest

from hyperdrive_tpu.codec import SerdeError
from hyperdrive_tpu.harness import Simulation
from hyperdrive_tpu.process import Process
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CommitterCallback,
    MockProposer,
    MockValidator,
    random_state,
)
from hyperdrive_tpu.utils import NULL_TRACER, Histogram, Tracer, get_logger, kv
from hyperdrive_tpu.utils.checkpoint import (
    checkpoint_bytes,
    restore_bytes,
    restore_process,
    save_process,
)


# ---------------------------------------------------------------- trace


def test_counter_and_histogram_basics():
    t = Tracer(time_fn=lambda: 0.0)
    t.count("a")
    t.count("a", 4)
    t.observe("h", 0.5)
    t.observe("h", 1.5)
    snap = t.snapshot()
    assert snap["counters"]["a"] == 5
    assert snap["histograms"]["h"]["count"] == 2
    assert snap["histograms"]["h"]["mean"] == 1.0
    assert "a" in t.render() and "h" in t.render()


def test_histogram_quantiles():
    h = Histogram()
    for i in range(100):
        h.observe(i / 100.0)
    assert 0.4 <= h.quantile(0.5) <= 0.6
    assert h.quantile(0.99) >= 0.9
    assert h.quantile(0.0) == 0.0


def test_span_uses_injected_clock():
    now = [0.0]
    t = Tracer(time_fn=lambda: now[0])
    with t.span("work"):
        now[0] += 2.5
    assert t.snapshot()["histograms"]["work"]["mean"] == 2.5


def test_null_tracer_records_nothing():
    NULL_TRACER.count("x")
    NULL_TRACER.observe("y", 1.0)
    with NULL_TRACER.span("z"):
        pass
    snap = NULL_TRACER.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}


def test_simulation_produces_metrics():
    sim = Simulation(n=4, target_height=5, seed=71)
    res = sim.run()
    assert res.completed
    snap = sim.tracer.snapshot()
    # 4 replicas x 5 heights of commits.
    assert snap["counters"]["replica.commits"] == 4 * 5
    assert snap["histograms"]["replica.commit.rounds"]["count"] == 20
    # Virtual-time latencies are deterministic across identical runs.
    sim2 = Simulation(n=4, target_height=5, seed=71)
    sim2.run()
    assert sim2.tracer.snapshot() == snap


def test_equivocation_metrics_and_logging():
    from hyperdrive_tpu.messages import Propose

    sim = Simulation(n=4, target_height=2, seed=73)
    for _i, r in enumerate(sim.replicas):
        r.start()
    # Deliver one legit propose to replica 0, then a conflicting one.
    legit = None
    while sim.queue:
        to, msg = sim.queue.pop(0)
        sim.replicas[to].handle(msg)
        if isinstance(msg, Propose) and to == 0:
            legit = msg
            break
    assert legit is not None
    sim.replicas[0].handle(
        Propose(
            height=legit.height,
            round=legit.round,
            valid_round=legit.valid_round,
            value=b"\xaa" * 32,
            sender=legit.sender,
        )
    )
    snap = sim.tracer.snapshot()
    assert snap["counters"]["replica.caught.double_propose"] == 1


# ------------------------------------------------------------------ log


def test_get_logger_has_null_handler_and_no_duplicates():
    lg1 = get_logger()
    lg2 = get_logger()
    assert lg1 is lg2
    nulls = [h for h in lg1.handlers if isinstance(h, logging.NullHandler)]
    assert len(nulls) == 1


def test_kv_rendering():
    s = kv(height=3, value=b"\xab" * 32, flag=True)
    assert "height=3" in s
    assert "value=abababababababab" in s
    assert "flag=True" in s


# ----------------------------------------------------------- checkpoint


def _make_proc(seed: int = 1) -> Process:
    import random

    state = random_state(random.Random(seed))
    return Process(whoami=os.urandom(32), f=3, state=state)


def test_checkpoint_roundtrip_bytes():
    proc = _make_proc(5)
    blob = checkpoint_bytes(proc)
    restored = Process(whoami=b"\x00" * 32, f=0)
    restore_bytes(restored, blob)
    assert restored.whoami == proc.whoami
    assert restored.f == proc.f
    assert restored.state == proc.state


def test_checkpoint_roundtrip_file(tmp_path):
    proc = _make_proc(6)
    path = os.path.join(tmp_path, "ckpt.bin")
    save_process(proc, path)
    restored = Process(whoami=b"\x00" * 32, f=0)
    restore_process(restored, path)
    assert restored.state == proc.state
    # No temp files left behind.
    assert os.listdir(tmp_path) == ["ckpt.bin"]


@pytest.mark.parametrize(
    "corrupt",
    [
        lambda b: b"\x00" * len(b),  # bad magic
        lambda b: b[:1] + bytes([b[1] ^ 1]) + b[2:],  # flipped magic byte
        lambda b: b[:6] + bytes([b[6] ^ 1]) + b[7:],  # wrong version
        lambda b: b[:-3],  # truncated payload
        lambda b: b[:-1] + bytes([b[-1] ^ 0xFF]),  # payload bit flip (crc)
        lambda b: b[:20],  # header only
    ],
)
def test_checkpoint_corruption_detected(corrupt):
    proc = _make_proc(7)
    blob = corrupt(checkpoint_bytes(proc))
    target = Process(whoami=b"\x11" * 32, f=9)
    before_whoami, before_f = target.whoami, target.f
    with pytest.raises(SerdeError):
        restore_bytes(target, blob)
    # A failed restore must not have mutated the target.
    assert target.whoami == before_whoami and target.f == before_f


def test_restart_mid_consensus_rejoins(tmp_path):
    """A replica checkpointed mid-run, 'crashed', and restored from the file
    continues committing with identical values (the reference's
    crash-restart contract, process/state.go:18-20). Uses a single-validator
    network (n=1, f=0) so one Process drives itself via its own broadcasts.
    """
    from hyperdrive_tpu.messages import Precommit, Prevote, Propose
    from hyperdrive_tpu.scheduler import RoundRobin

    path = os.path.join(tmp_path, "proc.ckpt")
    sig = b"\x07" * 32

    def build(commits):
        queue = []
        proc = Process(
            whoami=sig,
            f=0,
            scheduler=RoundRobin([sig]),
            proposer=MockProposer(fn=lambda h, r: bytes([h % 256]) * 32),
            validator=MockValidator(ok=True),
            broadcaster=BroadcasterCallbacks(
                on_propose=queue.append,
                on_prevote=queue.append,
                on_precommit=queue.append,
            ),
            committer=CommitterCallback(
                on_commit=lambda h, v: (commits.__setitem__(h, v), (0, None))[1]
            ),
        )
        return proc, queue

    def drive(proc, queue, until_height):
        for _ in range(10_000):
            if proc.current_height >= until_height or not queue:
                break
            msg = queue.pop(0)
            if isinstance(msg, Propose):
                proc.propose(msg)
            elif isinstance(msg, Prevote):
                proc.prevote(msg)
            elif isinstance(msg, Precommit):
                proc.precommit(msg)

    commits_a: dict[int, bytes] = {}
    proc, queue = build(commits_a)
    proc.start()
    drive(proc, queue, until_height=4)
    assert proc.current_height >= 4
    save_process(proc, path)

    # "Crash": rebuild fresh, restore, and continue to height 7.
    commits_b: dict[int, bytes] = {}
    proc2, queue2 = build(commits_b)
    restore_process(proc2, path)
    assert proc2.state == proc.state
    assert proc2.current_height == proc.current_height
    proc2.start_round(0)
    drive(proc2, queue2, until_height=7)
    assert proc2.current_height >= 7
    # Values committed after restart are exactly what an uninterrupted run
    # commits (deterministic by-height values).
    for h, v in commits_b.items():
        assert v == bytes([h % 256]) * 32


def test_checkpoint_semantic_corruption_leaves_proc_untouched():
    # A payload that passes the envelope CRC but fails mid-State-parse must
    # not leave the Process torn (whoami/f updated, state old). Rebuild a
    # valid envelope around a truncated payload body so only the inner
    # State.unmarshal raises.
    import zlib

    from hyperdrive_tpu.codec import Writer

    proc = _make_proc(8)
    blob = checkpoint_bytes(proc)
    payload = blob[20:-7]  # cut into the State section
    head = Writer(rem=64)
    head.u32(0x48594350)
    head.u32(1)
    head.u64(len(payload))
    head.u32(zlib.crc32(payload) & 0xFFFFFFFF)
    evil = head.data() + payload

    target = Process(whoami=b"\x11" * 32, f=9)
    before_state = target.state.clone()
    with pytest.raises(SerdeError):
        restore_bytes(target, evil)
    assert target.whoami == b"\x11" * 32
    assert target.f == 9
    assert target.state == before_state


def test_checkpoint_store_roundtrip(tmp_path):
    from hyperdrive_tpu.utils.checkpoint import CheckpointStore

    store = CheckpointStore()
    assert len(store) == 0
    assert store.latest(0) is None
    target = _make_proc(11)
    before = target.state.clone()
    assert store.restore(0, target) is False
    assert target.state == before  # untouched on a miss

    a, b = _make_proc(12), _make_proc(13)
    store.save(0, a)
    store.save(0, b)  # latest-wins per key
    assert len(store) == 1
    restored = Process(whoami=b"\x00" * 32, f=0)
    assert store.restore(0, restored) is True
    assert restored.state == b.state and restored.whoami == b.whoami

    paths = store.dump(os.path.join(tmp_path, "ckpts"))
    assert [os.path.basename(p) for p in paths] == ["replica_0.ckpt"]
    from_file = Process(whoami=b"\x00" * 32, f=0)
    restore_process(from_file, paths[0])
    assert from_file.state == b.state


def test_restore_mid_round_locked_value_no_equivocation():
    """Crash-restore a Process that LOCKED a value mid-round (ISSUE 5
    satellite): the restored replica re-arms its precommit timeout
    without re-broadcasting anything, and in the next round its
    restored lock steers it to prevote NIL against a different
    proposal (paper L28/L22 locking rules) — equivocation-free."""
    from hyperdrive_tpu.messages import Precommit, Prevote, Propose
    from hyperdrive_tpu.scheduler import RoundRobin
    from hyperdrive_tpu.types import NIL_VALUE, Step

    sigs = [bytes([i + 1]) * 32 for i in range(4)]
    me = sigs[0]
    v_locked = b"\xaa" * 32

    class CaptureTimer:
        def __init__(self):
            self.armed = []

        def timeout_propose(self, h, r):
            self.armed.append(("propose", h, r))

        def timeout_prevote(self, h, r):
            self.armed.append(("prevote", h, r))

        def timeout_precommit(self, h, r):
            self.armed.append(("precommit", h, r))

    def build():
        sent = []
        timer = CaptureTimer()
        proc = Process(
            whoami=me,
            f=1,
            timer=timer,
            scheduler=RoundRobin(sigs),
            proposer=MockProposer(fn=lambda h, r: b"\xee" * 32),
            validator=MockValidator(ok=True),
            broadcaster=BroadcasterCallbacks(
                on_propose=sent.append,
                on_prevote=sent.append,
                on_precommit=sent.append,
            ),
            committer=CommitterCallback(on_commit=lambda h, v: (0, None)),
        )
        return proc, sent, timer

    proc, sent, _ = build()
    proc.start()  # proposer of (1, 0) is sigs[1]; we arm timeout_propose
    proc.propose(
        Propose(
            height=1,
            round=0,
            valid_round=-1,
            value=v_locked,
            sender=sigs[1],
        )
    )
    for s in sigs[1:]:  # 2f+1 prevotes -> L36: lock v at round 0
        proc.prevote(Prevote(height=1, round=0, value=v_locked, sender=s))
    assert proc.state.locked_value == v_locked
    assert proc.state.locked_round == 0
    assert proc.state.current_step == Step.PRECOMMITTING
    blob = checkpoint_bytes(proc)

    # Crash: fresh wiring, restore, resume. No broadcast may happen —
    # a re-sent round-0 vote is exactly the double-send the catcher
    # would flag as equivocation.
    proc2, sent2, timer2 = build()
    restore_bytes(proc2, blob)
    assert proc2.state.locked_value == v_locked
    assert proc2.state.current_step == Step.PRECOMMITTING
    proc2.resume()
    assert sent2 == []
    assert timer2.armed == [("precommit", 1, 0)]

    # The quorum moved on: precommit timeout fires, round 1 starts
    # (proposer sigs[2]), and a DIFFERENT value is proposed. The
    # restored lock must answer with a NIL prevote (L22).
    proc2.on_timeout_precommit(1, 0)
    assert proc2.state.current_round == 1
    proc2.propose(
        Propose(
            height=1,
            round=1,
            valid_round=-1,
            value=b"\xcc" * 32,
            sender=sigs[2],
        )
    )
    nil_prevotes = [
        m
        for m in sent2
        if isinstance(m, Prevote) and m.round == 1
    ]
    assert [m.value for m in nil_prevotes] == [NIL_VALUE]
    # And nothing from round 0 was ever re-broadcast after restore.
    assert not any(
        isinstance(m, (Prevote, Precommit)) and m.round == 0 for m in sent2
    )
    assert not any(isinstance(m, Propose) for m in sent2)
