"""The multi-tenant verify service (parallel/service.py).

Everything here is jax-free by construction: tenants run unsigned
windows through a NullVerifier, so the tests exercise the service's own
machinery — tenant accounting, certificate watermarks, the drain-policy
seam, and the cross-process TCP port — not the crypto underneath it
(test_ed25519* own that).
"""

import dataclasses
import struct
import time

import pytest

from hyperdrive_tpu.codec import SerdeError
from hyperdrive_tpu.devsched import DeficitRoundRobin
from hyperdrive_tpu.obs.devtel import DeviceTelemetry
from hyperdrive_tpu.parallel.service import (
    RemoteServiceClient,
    STATUS_COMMITTED,
    STATUS_NO_STATE,
    STATUS_SHED,
    STATUS_UNKNOWN_TENANT,
    ShardVerifyService,
    TenantShard,
    decode_proof,
    decode_request,
    decode_result,
    encode_hello,
    encode_proof,
    encode_query,
    encode_result,
    encode_submit,
)
from hyperdrive_tpu.verifier import NullVerifier


def _service(**kwargs):
    return ShardVerifyService(NullVerifier(), max_depth=0, **kwargs)


def _drive(service, shards, max_inflight=2, rounds=10_000):
    for _ in range(rounds):
        if all(s.done for s in shards):
            return
        for s in shards:
            s.pump(max_inflight=max_inflight)
        service.drain()
    raise AssertionError("tenants did not finish")


def _pump_until(port, n=1, deadline=5.0):
    """Service the port's inbox until ``n`` requests were handled (the
    reader thread delivers asynchronously; the drive loop polls)."""
    t0 = time.monotonic()
    handled = 0
    while handled < n:
        handled += port.pump()
        if time.monotonic() - t0 > deadline:
            raise AssertionError(f"port handled {handled}/{n} requests")
        if handled < n:
            time.sleep(0.001)
    return handled


# ------------------------------------------------- commit latency legs


def test_accept_certificate_records_commit_leg():
    devtel = DeviceTelemetry()
    svc = _service(devtel=devtel)
    shard = TenantShard("a", target_height=3, sign=False).attach_local(svc)
    _drive(svc, [shard])
    assert shard.done and shard.rejected == 0
    tid = svc.tenant_ids["a"]
    committed = devtel.registry.histograms["tenant.commit.latency"]
    assert committed[tid].total == 3
    # No rejection ever happened, so the rejected-path histogram must
    # not even exist — a failed verify is the ONLY thing that feeds it.
    assert "tenant.commit_rejected.latency" not in devtel.registry.histograms


def test_accept_certificate_rejected_leg_is_separate():
    devtel = DeviceTelemetry()
    svc = _service(devtel=devtel)
    a = TenantShard("a", target_height=2, sign=False).attach_local(svc)
    b = TenantShard("b", target_height=2, sign=False).attach_local(svc)
    _drive(svc, [a, b])
    committed = devtel.registry.histograms["tenant.commit.latency"]
    a_tid = svc.tenant_ids["a"]
    before = committed[a_tid].total
    # A tampered certificate (value swapped after minting) breaks the
    # binding recomputation — the O(1) verify must reject it AND record
    # the latency on the rejected leg, leaving the committed-path
    # histogram untouched.
    import dataclasses

    forged = dataclasses.replace(
        svc.certificates["b"][1], value_digest=b"\x13" * 32
    )
    assert not svc.accept_certificate("a", a.certifier, forged)
    assert committed[a_tid].total == before
    rejected = devtel.registry.histograms["tenant.commit_rejected.latency"]
    assert rejected[a_tid].total == 1
    # The forged cert never lands in the table.
    assert svc.certificates["a"][1] is not forged


# -------------------------------------------- watermark retirement soak


def test_watermark_retirement_bounds_state_over_10k_heights():
    keep = 32
    svc = _service(cert_keep=keep)
    shard = TenantShard(
        "soak", target_height=10_000, sign=False
    ).attach_local(svc)
    peak = 0
    for _ in range(10_000):
        if shard.done:
            break
        shard.pump(max_inflight=8)
        svc.drain()
        peak = max(peak, len(svc.certificates["soak"]))
    assert shard.done and shard.rejected == 0
    assert svc.watermarks["soak"] == 10_000
    # Retention stays bounded by the watermark window the whole run —
    # the service is O(tenants), not O(heights).
    assert peak <= keep + 8
    assert len(svc.certificates["soak"]) <= keep
    assert svc.retired_certs >= 10_000 - keep - 8
    # The tenant/id tables stay O(tenants) trivially.
    assert len(svc.tenants) == 1 and len(svc.tenant_ids) == 1


def test_retire_tenant_never_reuses_track_ids():
    svc = _service(cert_keep=4)
    a = TenantShard("a", target_height=2, sign=False).attach_local(svc)
    _drive(svc, [a])
    tid_a = svc.tenant_ids["a"]
    assert svc.retire_tenant("a") == 2
    assert "a" not in svc.certificates
    assert "a" not in svc.watermarks
    # A revived tenant gets a FRESH track id: journal tracks and
    # registry labels from its previous life must not be inherited.
    a2 = TenantShard("a", target_height=1, sign=False).attach_local(svc)
    _drive(svc, [a2])
    assert svc.tenant_ids["a"] != tid_a


# ------------------------------------------------------- digest parity


def test_shared_service_digest_matches_dedicated_queues():
    shared = _service(policy=DeficitRoundRobin(capacity_rows=8,
                                               quantum_rows=4))
    shards = [
        TenantShard(f"t{i}", target_height=5, sign=False).attach_local(shared)
        for i in range(3)
    ]
    _drive(shared, shards)
    for shard in shards:
        solo_svc = _service()
        solo = TenantShard(
            shard.name, target_height=5, sign=False
        ).attach_local(solo_svc)
        _drive(solo_svc, [solo])
        assert shard.commit_digest() == solo.commit_digest()


# ------------------------------------------------------ remote port/TCP


def test_remote_window_coalesces_with_local_tenants():
    devtel = DeviceTelemetry()
    svc = _service(devtel=devtel)
    local = TenantShard("local", target_height=1, sign=False)
    local.attach_local(svc)
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("remote", target_height=1, sign=False)
    remote.attach_remote(client)
    try:
        fut, value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=2)  # hello + submit parked then handled
        local.pump(max_inflight=1)
        svc.drain()
        status, mask, cert = fut.result(timeout=5.0)
        assert status == STATUS_COMMITTED
        assert all(mask)
        # The acceptance criterion itself: the remote tenant's window
        # rode the SAME launch as the local tenant's, visible in the
        # launch probe's origin tracks.
        both = {svc.tenant_ids["local"], svc.tenant_ids["remote"]}
        assert any(both <= set(r.origins) for r in devtel.records)
        # ...and its commit finalizes client-side from the O(1)
        # certificate frame alone.
        assert cert is not None and remote.certifier.verify(cert)
        assert port.remote_resolves == 1
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_digest_parity_with_local_run():
    svc = _service()
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("par", target_height=4, sign=False)
    remote.attach_remote(client)
    import threading

    t = threading.Thread(
        target=remote.run_remote, kwargs={"timeout": 10.0}, daemon=True
    )
    t.start()
    deadline = time.monotonic() + 10.0
    while t.is_alive() and time.monotonic() < deadline:
        port.pump()
        svc.drain()
        time.sleep(0.001)
    t.join(1.0)
    client.close()
    port.close()
    assert remote.done
    solo_svc = _service()
    solo = TenantShard("par", target_height=4, sign=False)
    solo.attach_local(solo_svc)
    _drive(solo_svc, [solo])
    assert remote.commit_digest() == solo.commit_digest()
    # Server-side accounting converged with the client's view.
    assert svc.watermarks["par"] == 4
    assert port.inflight == 0


def test_remote_submit_without_hello_is_unknown_tenant():
    svc = _service()
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    try:
        shard = TenantShard("ghost", target_height=1, sign=False)
        fut = client.submit(1, 0, shard.value_at(1), shard.window(1))
        _pump_until(port, n=1)
        status, mask, cert = fut.result(timeout=5.0)
        assert status == STATUS_UNKNOWN_TENANT
        assert cert is None and not any(mask)
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_critical_backpressure_sheds_without_touching_queue():
    from hyperdrive_tpu.load.backpressure import (
        CRITICAL_ONLY,
        BackpressureController,
    )

    svc = _service()
    controller = BackpressureController()
    controller.watch(svc.queue)
    controller.floor = CRITICAL_ONLY
    port = svc.remote_port(controller=controller)
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("shed", target_height=1, sign=False)
    remote.attach_remote(client)
    try:
        fut, _value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=2)
        status, _mask, cert = fut.result(timeout=5.0)
        assert status == STATUS_SHED and cert is None
        assert port.remote_sheds == 1
        # Flow control, not loss: the queue never saw the window.
        assert svc.queue.depth == 0 and svc.tenants == {}
        # Pressure released -> the SAME window goes through (the client
        # retry path run by hand). De-escalation is hysteretic: the
        # level only steps down after `hysteresis` consecutive calm
        # polls, exactly like the load/ soaks.
        controller.floor = 0
        for _ in range(controller.hysteresis):
            controller.poll()
        fut2, value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=1)
        svc.drain()
        status2, mask2, cert2 = fut2.result(timeout=5.0)
        assert status2 == STATUS_COMMITTED and all(mask2)
        assert remote.certifier.verify(cert2)
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_replay_of_committed_height_sheds_as_stale():
    from hyperdrive_tpu.load.backpressure import (
        SHED_DUPLICATES,
        BackpressureController,
    )

    svc = _service()
    controller = BackpressureController()
    controller.watch(svc.queue)
    port = svc.remote_port(controller=controller)
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("rep", target_height=1, sign=False)
    remote.attach_remote(client)
    try:
        fut, _value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=2)
        svc.drain()
        assert fut.result(timeout=5.0)[0] == STATUS_COMMITTED
        # Under duplicate-shedding pressure, a replay of the finalized
        # height classifies stale against the tenant's watermark (the
        # gate's height_fn) and the whole window sheds.
        controller.floor = SHED_DUPLICATES
        remote.next_height = 1
        fut2, _value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=1)
        status, _mask, cert = fut2.result(timeout=5.0)
        assert status == STATUS_SHED and cert is None
        assert svc.watermarks["rep"] == 1
    finally:
        client.close()
        port.close()
        svc.close()


# ------------------------------------------------------------- the wire


def test_wire_roundtrip_hello_submit_result():
    shard = TenantShard("w", n_validators=5, target_height=1, sign=False)
    kind, name, f, sigs, t0 = decode_request(
        encode_hello("w", shard.ring.signatories, shard.f)
    )
    assert t0 == 0.0
    assert (kind, name, f) == ("hello", "w", shard.f)
    assert sigs == list(shard.ring.signatories)

    rows = shard.window(3)
    kind, req_id, h, rnd, value, gen, pairs = decode_request(
        encode_submit(7, 3, 1, shard.value_at(3), rows, generation=2)
    )
    assert (kind, req_id, h, rnd, gen) == ("submit", 7, 3, 1, 2)
    assert value == shard.value_at(3)
    assert pairs == [(pc.sender, pc.signature) for pc in rows]

    mask = [True, False, True, True, False]
    req_id, status, got_mask, cert, root = decode_result(
        encode_result(9, STATUS_COMMITTED, 5, mask)
    )
    assert (req_id, status, cert, root) == (9, STATUS_COMMITTED, None, None)
    assert got_mask == mask

    # A root-stamped frame round-trips the 32 bytes; a wrong-width root
    # is malformed on its face.
    stamped = encode_result(9, STATUS_COMMITTED, 5, mask,
                            root=b"\x42" * 32)
    assert decode_result(stamped)[4] == b"\x42" * 32
    with pytest.raises(SerdeError):
        decode_result(
            encode_result(9, STATUS_COMMITTED, 5, mask, root=b"\x42" * 8)
        )


def test_wire_result_carries_certificate():
    svc = _service()
    shard = TenantShard("c", target_height=1, sign=False).attach_local(svc)
    _drive(svc, [shard])
    cert = svc.certificates["c"][1]
    _req, _status, _mask, got, _root = decode_result(
        encode_result(1, STATUS_COMMITTED, 4, [True] * 4, cert)
    )
    assert got is not None
    assert (got.height, got.value_digest) == (cert.height, cert.value_digest)
    assert shard.certifier.verify(got)


def test_wire_rejects_malformed_and_overwide_frames():
    with pytest.raises(SerdeError):
        decode_request(b"\xff\x00junk")
    with pytest.raises(SerdeError):
        decode_request(b"")
    # Caps: a committee / window wider than the wire maxima must raise
    # before any per-row allocation happens.
    from hyperdrive_tpu.codec import Writer

    w = Writer()
    w.u8(2)          # TAG_SUBMIT
    w.u64(1)
    w.i64(1)
    w.i64(0)
    w.bytes32(b"\x00" * 32)
    w.u32(0)
    w.u32(1 << 20)   # rows: over _MAX_ROWS
    with pytest.raises(SerdeError):
        decode_request(w.data())
    # Truncated mid-row submit.
    good = encode_submit(1, 1, 0, b"\x11" * 32,
                         [(b"\x22" * 32, b"\x01" * 64)])
    with pytest.raises(SerdeError):
        decode_request(good[:-10])
    with pytest.raises(SerdeError):
        decode_result(b"\x03\x00")


def test_port_counts_bad_frames_instead_of_dying():
    from hyperdrive_tpu.transport import _LEN

    svc = _service()
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("f", target_height=1, sign=False)
    remote.attach_remote(client)
    try:
        client._send(b"\xee\xeejunk")
        fut, _value, _t0 = remote._remote_submit(1)
        _pump_until(port, n=3)  # hello + junk + submit
        svc.drain()
        # The junk frame was counted and skipped; the real submit on the
        # same connection still commits.
        assert port.bad_frames == 1
        assert fut.result(timeout=5.0)[0] == STATUS_COMMITTED
        assert _LEN.size == 4  # the framing contract transport.py owns
    finally:
        client.close()
        port.close()
        svc.close()


# ------------------------------------------------ execution-layer hook


def _exec_cfg(seed=5):
    from hyperdrive_tpu.exec import ExecutionConfig

    return ExecutionConfig(
        accounts=16, txs_per_block=8, stake_every=3, stake_accounts=4,
        seed=seed,
    )


def test_local_tenant_commits_carry_state_roots():
    svc = _service()
    shard = TenantShard(
        "led", target_height=4, sign=False, execution=_exec_cfg()
    ).attach_local(svc)
    _drive(svc, [shard])
    assert shard.done and shard.rejected == 0
    # Every committed height carries the executor's chained root, and
    # the chain is exactly what a standalone executor derives from the
    # same config — the frame vouches for ledger state.
    from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

    ref = HostLedgerExecutor(_exec_cfg())
    for h in range(1, 5):
        assert shard.state_roots[h] == ref.advance_to(h)
    assert svc.executors["led"].applied_total == ref.applied_total


def test_remote_tenant_frames_carry_state_roots():
    svc = _service()
    svc.attach_execution("rx", _exec_cfg(seed=9))
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("rx", target_height=3, sign=False)
    remote.attach_remote(client)
    try:
        import threading

        t = threading.Thread(target=remote.run_remote, daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while not remote.done and time.monotonic() < deadline:
            port.pump()
            svc.drain()
            time.sleep(0.001)
        t.join(timeout=5.0)
        assert remote.done and remote.rejected == 0
        from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

        ref = HostLedgerExecutor(_exec_cfg(seed=9))
        for h in range(1, 4):
            assert remote.state_roots[h] == ref.advance_to(h)
    finally:
        client.close()
        port.close()
        svc.close()


def test_tenant_windows_ride_the_speculative_pipeline():
    # Execution-attached tenants apply each height's block at SUBMIT
    # time (exact unsigned speculation — no guessed mask, so no
    # rollback machinery on the serving path); the certificate accept
    # confirms-in-passing and reads the cached root. Digest-neutral:
    # the chain equals the non-speculative reference exactly.
    svc = _service()
    shard = TenantShard(
        "led", target_height=4, sign=False, execution=_exec_cfg()
    ).attach_local(svc)
    _drive(svc, [shard])
    assert shard.done and shard.rejected == 0
    ex = svc.executors["led"]
    assert ex.spec_confirmed == 4
    assert ex.spec_rolled_back == 0
    assert not ex._spec  # every window settled by commit time
    from hyperdrive_tpu.exec.ledger import HostLedgerExecutor

    ref = HostLedgerExecutor(_exec_cfg())
    for h in range(1, 5):
        assert shard.state_roots[h] == ref.advance_to(h)
    # Signed-tx configs are excluded: their admission mask is only
    # known post-verify, so submit-time speculation must decline.
    from hyperdrive_tpu.exec import ExecutionConfig

    signed = ExecutionConfig(
        accounts=16, txs_per_block=8, stake_every=3, stake_accounts=4,
        sign_txs=True,
    )
    svc.attach_execution("signed", signed)
    assert svc.speculate_height("signed", 1) is False
    assert svc.executors["signed"].spec_confirmed == 0


def test_rootless_tenant_unaffected_by_neighbors_ledger():
    # A tenant WITHOUT execution attached must see no root on its
    # frames and commit the byte-identical chain it commits solo —
    # another tenant's ledger must never leak across accounting keys.
    svc = _service()
    led = TenantShard(
        "led", target_height=3, sign=False, execution=_exec_cfg()
    ).attach_local(svc)
    plain = TenantShard("plain", target_height=3, sign=False)
    plain.attach_local(svc)
    _drive(svc, [led, plain])
    assert plain.state_roots == {}
    assert len(led.state_roots) == 3
    solo_svc = _service()
    solo = TenantShard("plain", target_height=3, sign=False)
    solo.attach_local(solo_svc)
    _drive(solo_svc, [solo])
    assert plain.commit_digest() == solo.commit_digest()


def test_epoch_rotation_mid_serve_keeps_roots_continuous():
    # The regression frontier: a service-wide epoch rotation lands
    # while an execution-attached tenant is mid-serve. The rotation
    # retags subsequent windows with the new generation; the tenant's
    # root chain must stay continuous across the boundary and the whole
    # run must match a rotation-free serve byte for byte.
    def serve(rotate_at):
        svc = _service()
        shard = TenantShard(
            "rot", target_height=6, sign=False, execution=_exec_cfg(seed=3)
        ).attach_local(svc)
        for _ in range(10_000):
            if shard.done:
                break
            if rotate_at is not None and len(shard.commits) >= rotate_at:
                svc.rotate(generation=1)
                shard.generation = 1
                rotate_at = None
            shard.pump(max_inflight=1)
            svc.drain()
        assert shard.done and shard.rejected == 0
        return shard

    rotated = serve(rotate_at=3)
    baseline = serve(rotate_at=None)
    assert sorted(rotated.state_roots) == list(range(1, 7))
    assert rotated.state_roots == baseline.state_roots
    assert rotated.commit_digest() == baseline.commit_digest()
    assert rotated.generation == 1


# ---------------------------------------- result-frame version back-compat


def _v15_encode_result(req_id, status, nrows, mask, root=b""):
    """The result-frame encoder EXACTLY as the v15-era client/server
    shipped it, frozen in struct calls (no shared code with the live
    codec, so a drift in either direction fails here). Layout: u8 tag,
    u64 req_id, u8 status, u32 nrows, raw bitmap, raw root, raw cert —
    ``raw`` being a u32 length prefix + bytes."""
    bitmap = bytearray(-(-nrows // 8)) if nrows else bytearray()
    for i, ok in enumerate(mask or ()):
        if ok:
            bitmap[i >> 3] |= 1 << (i & 7)
    out = struct.pack("<B", 3) + struct.pack("<Q", req_id)
    out += struct.pack("<B", status) + struct.pack("<I", nrows)
    out += struct.pack("<I", len(bitmap)) + bytes(bitmap)
    out += struct.pack("<I", len(root)) + bytes(root)
    out += struct.pack("<I", 0)  # no certificate tail
    return out


def _v15_decode_result(payload):
    """The v15-era client's decode, frozen: returns (req_id, status,
    mask, root_or_None) — certificate tails are skipped as the old
    reader did when the cert length prefix said empty."""
    off = 0
    (tag,) = struct.unpack_from("<B", payload, off); off += 1
    assert tag == 3
    (req_id,) = struct.unpack_from("<Q", payload, off); off += 8
    (status,) = struct.unpack_from("<B", payload, off); off += 1
    (n,) = struct.unpack_from("<I", payload, off); off += 4
    (blen,) = struct.unpack_from("<I", payload, off); off += 4
    bitmap = payload[off:off + blen]; off += blen
    mask = [bool(bitmap[i >> 3] >> (i & 7) & 1) for i in range(n)]
    (rlen,) = struct.unpack_from("<I", payload, off); off += 4
    root = payload[off:off + rlen] or None
    return req_id, status, mask, root


def test_result_frame_back_compat_across_versions():
    # TAG_QUERY is a NEW tag; the result frame itself must be
    # byte-identical in both directions so a v15-era peer and this
    # build interoperate on the submit path unchanged.
    mask = [True, False, True, False, True]
    live = encode_result(5, STATUS_COMMITTED, 5, mask, root=b"\x42" * 32)
    frozen = _v15_encode_result(
        5, STATUS_COMMITTED, 5, mask, root=b"\x42" * 32
    )
    assert live == frozen  # new server -> old client, byte for byte
    # Old server -> new client: the live decoder accepts the frozen
    # bytes and reads the same fields.
    req_id, status, got_mask, cert, root = decode_result(frozen)
    assert (req_id, status, cert) == (5, STATUS_COMMITTED, None)
    assert got_mask == mask and root == b"\x42" * 32
    # Old client -> frozen decode of the live bytes agrees too.
    assert _v15_decode_result(live) == (
        5, STATUS_COMMITTED, mask, b"\x42" * 32
    )
    # Rootless frames (the v15 default) as well.
    assert encode_result(9, STATUS_SHED, 3, ()) == _v15_encode_result(
        9, STATUS_SHED, 3, ()
    )


# ------------------------------------------------- trustless read path


def test_wire_roundtrip_query_and_proof():
    kind, req_id, account = decode_request(encode_query(11, 7))
    assert (kind, req_id, account) == ("query", 11, 7)
    # Status-only refusals carry no body.
    rid, status, proof = decode_proof(encode_proof(4, STATUS_NO_STATE))
    assert (rid, status, proof) == (4, STATUS_NO_STATE, None)
    # A full proof round-trips field for field.
    from hyperdrive_tpu.ops.merkle import MerkleProof

    p = MerkleProof(
        height=3, account=7, balance=123, stake=-4,
        prev_root=b"\x05" * 32, digest=tuple(range(8)),
        siblings=((1, 2, 3, 4), (5, 6, 7, 8), (9, 10, 11, 12)),
    )
    rid, status, got = decode_proof(encode_proof(8, STATUS_COMMITTED, p))
    assert (rid, status, got) == (8, STATUS_COMMITTED, p)
    # Byzantine depth: a path deeper than MAX_DEPTH raises before any
    # per-sibling allocation.
    from hyperdrive_tpu.codec import Writer
    from hyperdrive_tpu.ops.merkle import MAX_DEPTH

    w = Writer()
    w.u8(4)  # TAG_QUERY
    w.u64(1)
    w.u8(STATUS_COMMITTED)
    w.i64(1)
    w.u32(0)
    w.i64(0)
    w.i64(0)
    w.bytes32(b"\x00" * 32)
    w.raw(b"\x00" * 32)
    w.u32(MAX_DEPTH + 1)
    w.raw(b"")
    with pytest.raises(SerdeError):
        decode_proof(w.data())


def _proof_port(target_height=3, seed=9):
    """Spin a service + port + remote execution-attached tenant driven
    to ``target_height``; returns (svc, port, client, remote)."""
    import threading

    svc = _service()
    svc.attach_execution("rx", _exec_cfg(seed=seed))
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("rx", target_height=target_height, sign=False)
    remote.attach_remote(client)
    t = threading.Thread(target=remote.run_remote, daemon=True)
    t.start()
    deadline = time.monotonic() + 10.0
    while not remote.done and time.monotonic() < deadline:
        port.pump()
        svc.drain()
        time.sleep(0.001)
    t.join(timeout=5.0)
    assert remote.done and remote.rejected == 0
    return svc, port, client, remote


def _query(port, svc, client, account):
    fut = client.query(account)
    deadline = time.monotonic() + 5.0
    while not fut.done() and time.monotonic() < deadline:
        port.pump()
        svc.drain()
        time.sleep(0.001)
    return fut.proof_result(timeout=1.0)


def test_remote_query_serves_verifiable_proof():
    svc, port, client, remote = _proof_port()
    try:
        status, proof = _query(port, svc, client, 3)
        assert status == STATUS_COMMITTED
        # The client verifies against the root it ALREADY trusts from
        # the certificate chain — zero trust in the serving replica.
        trusted = remote.state_roots[proof.height]
        assert remote.verify_balance(proof, trusted)
        assert proof.balance >= 0 and len(proof.siblings) == 4  # 16 accts
        assert port.remote_queries == 1 and port.query_sheds == 0
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_query_detects_all_four_forged_proof_variants():
    from hyperdrive_tpu.ops.merkle import verify_inclusion

    svc, port, client, remote = _proof_port(seed=7)
    try:
        status, proof = _query(port, svc, client, 5)
        assert status == STATUS_COMMITTED
        trusted = remote.state_roots[proof.height]
        assert verify_inclusion(
            trusted, 5, proof.balance, proof.stake, proof
        )
        # A Byzantine server's four classic forgeries, applied to the
        # real frame the wire delivered — each must fail the client's
        # recomputation against the trusted root.
        stale = dataclasses.replace(proof, prev_root=b"\x00" * 32)
        forged = dataclasses.replace(
            proof, siblings=((1, 2, 3, 4),) + proof.siblings[1:]
        )
        truncated = dataclasses.replace(
            proof, siblings=proof.siblings[:-1]
        )
        wrong_leaf = dataclasses.replace(proof, balance=proof.balance + 1)
        for bad in (stale, forged, truncated, wrong_leaf):
            assert not verify_inclusion(
                trusted, 5, bad.balance, bad.stake, bad
            )
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_query_before_first_certificate_is_no_state():
    svc = _service()
    svc.attach_execution("rx", _exec_cfg())
    port = svc.remote_port()
    client = RemoteServiceClient(*port.address)
    remote = TenantShard("rx", target_height=1, sign=False)
    remote.attach_remote(client)
    try:
        client.hello("rx", remote.ring.signatories, remote.f)
        status, proof = _query(port, svc, client, 0)
        assert status == STATUS_NO_STATE and proof is None
        # Rootless tenants (no execution attached) answer the same way.
        assert port.remote_queries == 0
    finally:
        client.close()
        port.close()
        svc.close()


def test_remote_query_sheds_under_pressure_and_recovers():
    from hyperdrive_tpu.load.backpressure import SHED_LOW_PRIORITY

    svc, port, client, remote = _proof_port()
    try:
        port.controller.floor = SHED_LOW_PRIORITY
        port.controller.poll()
        status, proof = _query(port, svc, client, 2)
        assert status == STATUS_SHED and proof is None
        assert port.query_sheds == 1
        # Pressure released -> the same retried query serves (reads are
        # flow-controlled, never lost).
        port.controller.floor = 0
        for _ in range(port.controller.hysteresis):
            port.controller.poll()
        status2, proof2 = _query(port, svc, client, 2)
        assert status2 == STATUS_COMMITTED
        assert remote.verify_balance(
            proof2, remote.state_roots[proof2.height]
        )
    finally:
        client.close()
        port.close()
        svc.close()
