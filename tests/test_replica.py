"""Replica driver: loopback consensus, reentrancy, filters, reset, verifier.

The loopback tests wire broadcasters *synchronously* back into
``Replica.handle`` — the harshest reentrancy stress (the Go reference always
has a channel hop in between; our synchronous mode must serialize on its
own).
"""

import hashlib

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.replica import Replica, ReplicaOptions, ResetHeight
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockValidator,
    TimerCallbacks,
)


def keys(n):
    return [hashlib.sha256(f"replica-{i}".encode()).digest() for i in range(n)]


def block(h):
    return hashlib.sha256(f"block-{h}".encode()).digest()


def build_network(n, verifier_for=None, max_height=5):
    """n replicas; broadcasts are delivered synchronously to everyone.

    The proposer stops producing values past ``max_height`` so the fully
    synchronous cascade terminates (a perfect lossless loopback network
    would otherwise commit heights forever).
    """
    sigs = keys(n)
    commits = {i: {} for i in range(n)}
    replicas = []

    def deliver(msg):
        for r in replicas:
            r.handle(msg)

    def proposer_fn(h, r):
        return block(h) if h <= max_height else b"\x00" * 32  # NIL past cap

    for i in range(n):
        broadcaster = BroadcasterCallbacks(
            on_propose=deliver, on_prevote=deliver, on_precommit=deliver
        )
        committer = CommitterCallback(
            on_commit=lambda h, v, i=i: (commits[i].__setitem__(h, v), (0, None))[1]
        )
        replicas.append(
            Replica(
                ReplicaOptions(),
                sigs[i],
                list(sigs),
                TimerCallbacks(),
                MockProposer(fn=proposer_fn),
                MockValidator(ok=True),
                committer,
                CatcherCallbacks(),
                broadcaster,
                verifier=(verifier_for(i) if verifier_for else None),
            )
        )
    return sigs, replicas, commits


def test_loopback_consensus_with_reentrant_broadcasts():
    # Starting every replica triggers a fully synchronous cascade: the
    # proposer's broadcast reenters every replica's handle() which
    # rebroadcasts prevotes/precommits... consensus should simply happen.
    _, replicas, commits = build_network(4)
    for r in replicas:
        r.start()
    # The cascade from start() alone drives the network through many
    # heights; every commit map must agree wherever it overlaps.
    heights = [r.current_height() for r in replicas]
    assert min(heights) > 1
    common = set.intersection(*(set(c.keys()) for c in commits.values()))
    assert common
    for h in common:
        assert len({commits[i][h] for i in commits}) == 1


def test_reentrant_handle_preserves_safety_at_scale():
    _, replicas, commits = build_network(7)
    for r in replicas:
        r.start()
    common = set.intersection(*(set(c.keys()) for c in commits.values()))
    for h in common:
        assert len({commits[i][h] for i in commits}) == 1


def test_height_filter_drops_past_messages():
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    r0.start()
    past = Prevote(height=0, round=0, value=b"\x01" * 32, sender=sigs[1])
    r0.handle(past)
    assert len(r0.mq) == 0


def test_future_messages_buffered_not_dispatched():
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    r0.start()
    fut = Prevote(height=50, round=0, value=b"\x01" * 32, sender=sigs[1])
    r0.handle(fut)
    assert len(r0.mq) == 1
    assert 0 not in r0.proc.state.prevote_logs


def test_non_whitelisted_sender_filtered_on_flush():
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    r0.start()
    stranger = b"\x99" * 32
    r0.handle(Prevote(height=r0.current_height(), round=0,
                      value=b"\x01" * 32, sender=stranger))
    assert not any(
        stranger in votes for votes in r0.proc.state.prevote_logs.values()
    )


def test_did_handle_message_fires_per_message():
    sigs, replicas, _ = build_network(4)
    count = [0]
    r0 = replicas[0]
    r0.did_handle_message = lambda: count.__setitem__(0, count[0] + 1)
    r0.start()
    r0.handle(Prevote(height=r0.current_height(), round=0,
                      value=b"\x01" * 32, sender=sigs[1]))
    r0.handle(Prevote(height=r0.current_height(), round=0,
                      value=b"\x02" * 32, sender=sigs[2]))
    assert count[0] == 2


def test_reset_height_jumps_and_rotates():
    sigs, replicas, _ = build_network(4)
    r0 = replicas[0]
    r0.start()
    new_sigs = keys(7)
    r0.handle(ResetHeight(height=100, signatories=tuple(new_sigs)))
    assert r0.current_height() == 100
    assert r0.proc.f == 2  # 7 // 3
    assert r0.procs_allowed == set(new_sigs)


def test_f_computed_from_signatory_count():
    for n, want_f in [(4, 1), (7, 2), (10, 3), (16, 5)]:
        _, replicas, _ = build_network(n)
        assert replicas[0].proc.f == want_f


class RecordingVerifier:
    """Accepts everything; records batch sizes (device-free stand-in)."""

    def __init__(self):
        self.batches = []

    def verify_batch(self, window):
        self.batches.append(len(window))
        return [True] * len(window)


class RejectingVerifier:
    def verify_batch(self, window):
        return [False] * len(window)


def test_verifier_window_path_dispatches_survivors():
    ver = RecordingVerifier()
    sigs, replicas, commits = build_network(4, verifier_for=lambda i: ver)
    for r in replicas:
        r.start()
    # Consensus must still work through the batched drain path.
    common = set.intersection(*(set(c.keys()) for c in commits.values()))
    assert common
    assert ver.batches and all(b >= 1 for b in ver.batches)


def test_rejecting_verifier_blocks_progress():
    sigs, replicas, commits = build_network(4, verifier_for=lambda i: RejectingVerifier())
    for r in replicas:
        r.start()
    # Nothing verified -> no prevotes logged -> nobody commits.
    assert all(not c for c in commits.values())
