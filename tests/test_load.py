"""Open-loop overload harness: schedules, backpressure, admission.

The load package (hyperdrive_tpu/load/) is the robustness PR's spine:
seeded arrival schedules, the BackpressureController fusing pipeline
signals into one admission level, the AdmissionGate's shed-class
doctrine (ROBUSTNESS.md "Overload doctrine"), and the sim-side
injector whose storms must never bend the committed chain.
"""

import dataclasses

from hyperdrive_tpu.devsched import DeviceWorkQueue, NullVerifyLauncher
from hyperdrive_tpu.load import (
    ACCEPT,
    CRITICAL_ONLY,
    SHED_DUPLICATES,
    SHED_LOW_PRIORITY,
    AdmissionGate,
    BackpressureController,
    BurstSchedule,
    LoadProfile,
    PoissonSchedule,
)
from hyperdrive_tpu.load.generator import LoadRuntime
from hyperdrive_tpu.messages import Precommit, Prevote, Propose


def _pv(sender=b"\x01", height=5, round_=0, value=b"\x07"):
    return Prevote(
        height=height, round=round_, value=value * 32, sender=sender * 32
    )


def _pinned(level):
    ctrl = BackpressureController()
    ctrl.floor = level
    ctrl.poll()
    return ctrl


# ---------------------------------------------------------------- schedules


def test_poisson_schedule_is_seeded_and_ascending():
    a = PoissonSchedule(2000.0, seed=9).arrivals(0.5)
    b = PoissonSchedule(2000.0, seed=9).arrivals(0.5)
    c = PoissonSchedule(2000.0, seed=10).arrivals(0.5)
    assert a == b and a != c
    assert all(0.0 <= t < 0.5 for t in a)
    assert a == sorted(a)
    # Poisson at rate R over horizon H offers ~R*H arrivals.
    assert 700 <= len(a) <= 1300


def test_burst_schedule_clumps_arrivals():
    sched = BurstSchedule(3200.0, burst=32, seed=4)
    arrivals = sched.arrivals(0.25)
    assert arrivals == BurstSchedule(3200.0, burst=32, seed=4).arrivals(0.25)
    # Periodic spikes: every arrival shares its timestamp with its whole
    # burst, so the set of distinct times is len/burst.
    assert len(arrivals) % 32 == 0
    assert len(set(arrivals)) == len(arrivals) // 32


def test_load_runtime_caps_and_carries_excess():
    rt = LoadRuntime(LoadProfile(rate=1000.0, seed=3, amp_cap=16))
    # A big clock jump makes ~1000 arrivals due; each call hands out at
    # most amp_cap and the rest stays due — offered load is never
    # silently discarded.
    first = rt.due(1.0)
    assert first == 16
    total = first
    while True:
        k = rt.due(1.0)
        if not k:
            break
        assert k <= 16
        total += k
    assert total == rt.offered
    assert 800 <= total <= 1200
    # Past the window's stop nothing is due.
    rt2 = LoadRuntime(LoadProfile(rate=1000.0, seed=3, stop=0.5))
    assert rt2.due(0.75) == 0


# --------------------------------------------------------------- controller


def test_controller_escalates_on_depth_and_deescalates_with_hysteresis():
    ctrl = BackpressureController(hysteresis=3)
    assert ctrl.level == ACCEPT
    ctrl.note_depth(8)
    assert ctrl.level == SHED_DUPLICATES
    ctrl.note_depth(300)
    assert ctrl.level == CRITICAL_ONLY
    # Pressure gone: the level holds for hysteresis-1 clean polls, then
    # steps down (no flapping around a threshold).
    ctrl.note_depth(0)
    assert ctrl.level == CRITICAL_ONLY
    ctrl.poll()
    assert ctrl.level == CRITICAL_ONLY
    ctrl.poll()
    assert ctrl.level == ACCEPT
    assert ctrl.transitions == 3


def test_controller_floor_pins_level():
    ctrl = _pinned(SHED_DUPLICATES)
    assert ctrl.level == SHED_DUPLICATES
    for _ in range(10):
        ctrl.poll()
    assert ctrl.level == SHED_DUPLICATES  # never de-escalates below floor
    ctrl.note_peer_occupancy(0.95)
    assert ctrl.level == CRITICAL_ONLY  # but raw signals escalate above


def test_device_queue_feeds_controller_signals():
    queue = DeviceWorkQueue(max_depth=64)
    ctrl = BackpressureController(hysteresis=1)
    ctrl.watch(queue)
    launcher = NullVerifyLauncher()
    for _ in range(8):
        queue.submit(launcher, [b"x"])
    assert ctrl.level == SHED_DUPLICATES
    queue.drain()
    ctrl.poll()
    assert ctrl.level == ACCEPT  # drain resets depth; hysteresis=1


# --------------------------------------------------------------------- gate


def test_gate_sheds_duplicates_and_stale_heights():
    gate = AdmissionGate(_pinned(SHED_DUPLICATES), height_fn=lambda: 5)
    pv = _pv()
    assert gate.admit(pv)
    assert not gate.admit(pv)  # exact duplicate
    assert not gate.admit(_pv(height=3))  # below the consumer's height
    assert gate.admit(_pv(value=b"\x08"))  # fresh vote still flows
    assert gate.shed == {"duplicate": 1, "stale_height": 1}


def test_gate_never_sheds_proposals_or_unknown_types():
    gate = AdmissionGate(_pinned(CRITICAL_ONLY), height_fn=lambda: 5)
    pp = Propose(
        height=5, round=0, valid_round=-1, value=b"\x07" * 32,
        sender=b"\x01" * 32, payload=b"",
    )
    assert gate.admit(pp)
    assert gate.admit(pp)  # even a duplicate proposal is never shed
    assert gate.admit(object())  # certificates/unknown kinds outrank votes
    pc = Precommit(
        height=5, round=0, value=b"\x07" * 32, sender=b"\x01" * 32
    )
    assert gate.admit(pc)  # precommits are quorum-forming: never panic-shed
    assert not gate.admit(_pv())  # fresh prevote sheds at CRITICAL_ONLY
    assert gate.shed == {"panic": 1}


def test_gate_per_peer_fairness_budget():
    gate = AdmissionGate(
        _pinned(SHED_LOW_PRIORITY), fair_window=8, fair_share=0.25
    )
    hog, meek = ("10.0.0.1", 1), ("10.0.0.2", 2)
    admitted_hog = sum(
        gate.admit(_pv(value=bytes([i])), peer=hog) for i in range(6)
    )
    assert admitted_hog == 2  # budget = fair_share * fair_window
    assert gate.shed["low_priority"] == 4
    # The budget is per peer: another peer's fresh votes still flow.
    assert gate.admit(_pv(sender=b"\x02", value=bytes([99])), peer=meek)


def test_gate_query_class_sheds_at_low_priority_never_ahead_of_certs():
    from hyperdrive_tpu.load.frames import QueryFrame

    # Calm and duplicate-shedding levels: reads flow, and an identical
    # re-query is NOT a duplicate (reads never enter dedup memory — a
    # retry after a shed is the doctrine, not replay spam).
    gate = AdmissionGate(_pinned(SHED_DUPLICATES), height_fn=lambda: 5)
    assert gate.admit(QueryFrame(account=3))
    assert gate.admit(QueryFrame(account=3))
    assert gate.shed == {}
    # From SHED_LOW_PRIORITY up, queries are the first prey — while the
    # never-shed kinds (certificates, proposals, precommits) still pass
    # even at CRITICAL_ONLY. A read storm cannot starve consensus.
    for level in (SHED_LOW_PRIORITY, CRITICAL_ONLY):
        gate = AdmissionGate(_pinned(level), height_fn=lambda: 5)
        assert not gate.admit(QueryFrame(account=3))
        assert gate.admit(object())  # certificate-like kinds
        assert gate.admit(
            Precommit(
                height=5, round=0, value=b"\x07" * 32, sender=b"\x01" * 32
            )
        )
        assert gate.shed == {"query": 1}


def test_gate_query_accounting_identity_and_memory_neutrality():
    from hyperdrive_tpu.load.frames import QueryFrame

    gate = AdmissionGate(_pinned(SHED_LOW_PRIORITY), height_fn=lambda: 5)
    for i in range(4):
        gate.admit(QueryFrame(account=i))
    gate.admit(_pv(value=b"\x08"))
    snap = gate.snapshot()
    assert snap["offered"] == snap["admitted"] + sum(snap["shed"].values())
    assert snap["shed"]["query"] == 4
    # Admitted queries never evict vote keys from the bounded memory.
    gate2 = AdmissionGate(_pinned(ACCEPT))
    for _ in range(8):
        assert gate2.admit(QueryFrame(account=0))
    assert gate2._mem == {}


def test_gate_accounting_identity():
    gate = AdmissionGate(_pinned(SHED_DUPLICATES), height_fn=lambda: 5)
    pv = _pv()
    for msg in (pv, pv, _pv(height=1), _pv(value=b"\x09")):
        gate.admit(msg)
    snap = gate.snapshot()
    assert snap["offered"] == snap["admitted"] + sum(snap["shed"].values())


# --------------------------------------------------------------- reputation


def test_reputation_charges_demote_and_amnesty_recovers():
    from hyperdrive_tpu.load.backpressure import SignerReputation

    rep = SignerReputation()  # weight 6, demote_at -8, floor -64
    assert rep.charge(b"\x05" * 32) == -6
    assert not rep.is_demoted(b"\x05" * 32)
    assert rep.charge(b"\x05" * 32) == -12  # crosses -8
    assert rep.is_demoted(b"\x05" * 32) and rep.demotions == 1
    # Per-commit amnesty repays 1 per height: demotion lifts only once
    # the score climbs back ABOVE the threshold (-7), never at it.
    for _ in range(4):
        rep.rehabilitate(1)
    assert rep.is_demoted(b"\x05" * 32)  # -8: still demoted
    rep.rehabilitate(1)
    assert not rep.is_demoted(b"\x05" * 32)  # -7: recovered
    assert rep.recoveries == 1
    # The floor clamps: a long storm's debt stays repayable.
    for _ in range(50):
        rep.charge(b"\x05" * 32)
    assert rep.scores[b"\x05" * 32] == -64


def test_reputation_credit_repays_verified_rows():
    from hyperdrive_tpu.load.backpressure import SignerReputation

    rep = SignerReputation()
    rep.charge(b"\x06" * 32)
    rep.charge(b"\x06" * 32)  # -12, demoted
    assert rep.credit(b"\x06" * 32, rows=4) == -8  # at threshold: demoted
    assert rep.is_demoted(b"\x06" * 32)
    assert rep.credit(b"\x06" * 32, rows=1) == -7
    assert not rep.is_demoted(b"\x06" * 32)
    # Credit never banks a positive balance for future forgery.
    assert rep.credit(b"\x06" * 32, rows=100) == 0


def test_gate_note_verify_feedback_sheds_demoted_prevotes_only():
    from hyperdrive_tpu.load.backpressure import SignerReputation

    rep = SignerReputation()
    gate = AdmissionGate(_pinned(ACCEPT), reputation=rep, height_fn=lambda: 5)
    forger = b"\x04" * 32
    pv = _pv(sender=b"\x04")
    assert gate.admit(pv, peer=forger)
    gate.note_verify(forger, False, 2)  # two failed rows -> demoted
    assert rep.is_demoted(forger)
    assert gate.verify_failed_by_peer[forger] == 2
    # Demoted prevotes shed under the reputation class even at ACCEPT.
    assert not gate.admit(_pv(sender=b"\x04", value=b"\x09"), peer=forger)
    assert gate.shed == {"reputation": 1}
    assert gate.shed_by_peer[forger] == 1
    # Scope is prevote-only: the same demoted peer's proposals and
    # precommits stay never-shed — demotion costs redundant votes,
    # never safety-critical reach.
    pp = Propose(
        height=5, round=0, valid_round=-1, value=b"\x07" * 32,
        sender=forger, payload=b"",
    )
    pc = Precommit(height=5, round=0, value=b"\x07" * 32, sender=forger)
    assert gate.admit(pp, peer=forger)
    assert gate.admit(pc, peer=forger)
    # Successful verifies repay the debt and reopen the gate.
    gate.note_verify(forger, True, 12)
    assert not rep.is_demoted(forger)
    assert gate.admit(_pv(sender=b"\x04", value=b"\x0a"), peer=forger)


# ---------------------------------------------------------------- sim storm


def test_loaded_sim_commits_identical_chain():
    from hyperdrive_tpu.harness.sim import Simulation

    def run(load):
        extra = {} if load is None else {"load": load}
        return Simulation(
            n=4, target_height=4, seed=17, timeout=1.0,
            delivery_cost=1e-3, **extra,
        )

    base = run(None).run()
    loaded_sim = run(LoadProfile(rate=4000.0, seed=17))
    loaded = loaded_sim.run()
    assert loaded.commit_digest() == base.commit_digest()
    snap = loaded_sim.overload_snapshot()
    assert snap["injected"] > 0
    # Only vote duplicates at un-advanced heights are guaranteed prey
    # (a burst landing on a proposal delivery is admitted by doctrine).
    assert 0 < snap["injected_sheddable"] <= snap["injected"]
    assert snap["shed"], "sheddable storm injected but nothing shed"
    assert set(snap["shed"]) <= {"duplicate", "stale_height"}
    assert snap["offered"] == snap["admitted"] + sum(snap["shed"].values())


def test_overload_profile_family_is_behavior_neutral():
    from hyperdrive_tpu.chaos.plan import FaultPlan

    plan, profile = FaultPlan.overload(77, 4)
    assert plan == FaultPlan.seeded(77, 4)
    assert profile.pin and profile.floor <= SHED_DUPLICATES
    # Same seed, same storm (the soak's reproducibility contract).
    _, again = FaultPlan.overload(77, 4)
    assert profile == again


def test_profile_seeded_rejects_trajectory_changing_floor():
    import pytest

    with pytest.raises(ValueError):
        LoadProfile(rate=0.0).validate()
    with pytest.raises(ValueError):
        LoadProfile(rate=100.0, burst=0).validate()
    with pytest.raises(ValueError):
        LoadProfile(rate=100.0, start=2.0, stop=1.0).validate()


def test_escalating_profile_keeps_safety():
    # pin=False couples the controller to the device queue; the chain
    # may reshape (prevotes become sheddable) but never forks.
    from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
    from hyperdrive_tpu.harness.sim import Simulation
    from hyperdrive_tpu.verifier import NullVerifier

    queue = DeviceWorkQueue(max_depth=96)
    sim = Simulation(
        n=4, target_height=3, seed=29, timeout=1.0, delivery_cost=1e-3,
        devsched=queue,
        flusher_for=lambda i, validators: QueueFlusher(
            NullVerifier(), queue
        ),
        load=dataclasses.replace(
            LoadProfile(rate=6000.0, seed=29), pin=False
        ),
    )
    res = sim.run()
    res.assert_safety()
    assert res.completed
    snap = sim.overload_snapshot()
    assert snap["offered"] == snap["admitted"] + sum(snap["shed"].values())
