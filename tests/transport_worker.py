"""Worker + shared builders for the loopback-TCP Broadcaster demo.

``python tests/transport_worker.py <portA> <portB> <rank> <target>
[mode]`` hosts replicas {0,1} (rank 0) or {2,3} (rank 1) of a
4-validator network on a :class:`hyperdrive_tpu.transport.TcpNode`, with
real wall-clock LinearTimer timeouts and signed messages verified per
replica — consensus across an OS process boundary with no shared memory.
Prints ``TRANSPORT_OK rank=<r> heights=<target> digest=<sha256>`` where
the digest covers the (identical) commit chains of both local replicas;
the parent test asserts the digests agree ACROSS processes.

``mode`` selects the verification stack:

- ``host`` (default): :class:`~hyperdrive_tpu.verifier.HostVerifier`
  per replica, no device involvement — pure host-code worker.
- ``tpu``: the deployment capstone. Every delivered envelope is
  verified through :class:`~hyperdrive_tpu.ops.ed25519_wire.
  TpuWireVerifier` with a resident ValidatorTable (the grouped
  69 B/lane challenge format: device SHA-512 + mod-L + decompression +
  ladder), and every replica's quorum counts come from its own n=1
  device vote grid (:class:`~hyperdrive_tpu.tallyflush.
  DeviceTallyFlusher`) with each device-sourced count cross-checked
  against the host counters (CheckedTallyView). The output line gains
  ``consulted=<device counts read> grouped=<69B-format lanes>``. This
  composes automaton + LinearTimer + TCP Broadcaster + TPU wire
  verifier + device vote grids in ONE multi-process run — the
  reference's full-network integration shape
  (/root/reference/replica/replica_test.go:372-430) on this
  framework's deployment stack.

The builders are imported by tests/test_transport.py for the in-process
4-node variant; in host mode this module must not import jax (the
transport layer is pure host code, and worker startup stays fast).
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.testutil import (
    CommitterCallback,
    MockProposer,
    MockValidator,
)
from hyperdrive_tpu.timer import LinearTimer
from hyperdrive_tpu.transport import TcpBroadcaster, TcpNode
from hyperdrive_tpu.verifier import HostVerifier


def deterministic_value(height, round_):
    return hashlib.sha256(b"txval-%d-%d" % (height, round_)).digest()


def build_replica(node: TcpNode, ring: KeyRing, i: int, target: int,
                  commits: dict, done: threading.Event,
                  timeout_s: float = 5.0, verifier=None,
                  flusher=None, recorder=None) -> Replica:
    """One threaded replica wired to the node: TcpBroadcaster (signing),
    LinearTimer (real wall-clock timeout threads), a Verifier (every
    delivered message's signature checked; HostVerifier by default),
    commit hook recording into ``commits`` and firing ``done`` at the
    target height. ``flusher`` plugs a device-tally flush delegate into
    the replica's flush seam (tpu mode)."""
    cell: dict = {}
    timer = LinearTimer(
        handle_timeout_propose=lambda t: cell["r"].timeout(t),
        handle_timeout_prevote=lambda t: cell["r"].timeout(t),
        handle_timeout_precommit=lambda t: cell["r"].timeout(t),
        timeout=timeout_s,
    )

    def on_commit(height, value):
        commits[height] = value
        if len(commits) >= target:
            done.set()
        return 0, None

    rep = Replica(
        ReplicaOptions(),
        whoami=ring[i].public,
        signatories=list(ring.signatories),
        timer=timer,
        proposer=MockProposer(fn=deterministic_value),
        validator=MockValidator(ok=True),
        committer=CommitterCallback(on_commit=on_commit),
        catcher=None,
        broadcaster=TcpBroadcaster(node, keypair=ring[i]),
        verifier=verifier if verifier is not None else HostVerifier(),
        flusher=flusher,
        recorder=recorder,
    )
    cell["r"] = rep
    node.add_replica(rep)
    return rep


def run_local_replicas(node: TcpNode, ring: KeyRing, indices, target: int,
                       deadline_s: float = 120.0, timeout_s: float = 5.0,
                       make_stack=None, coalesce: bool = False,
                       recorders: dict | None = None):
    """Run the given replica indices on ``node`` until every one commits
    ``target`` heights (or the deadline passes). Returns {index: commits}.

    ``make_stack(i) -> (verifier, flusher)`` supplies each replica's
    verification stack (tpu mode); ``coalesce`` batches each replica's
    inbox drains so a device-backed stack pays one launch per burst.
    ``recorders`` (a dict the caller owns) attaches a FlightRecorder per
    replica index — the socket run's offline-replay record, populated
    even when the run stalls (that is when you need it).
    """
    commits = {i: {} for i in indices}
    dones = {i: threading.Event() for i in indices}
    reps = []
    for i in indices:
        verifier = flusher = None
        if make_stack is not None:
            verifier, flusher = make_stack(i)
        recorder = None
        if recorders is not None:
            from hyperdrive_tpu.transport import FlightRecorder

            recorder = recorders[i] = FlightRecorder()
        reps.append(
            build_replica(node, ring, i, target, commits[i], dones[i],
                          timeout_s=timeout_s, verifier=verifier,
                          flusher=flusher, recorder=recorder)
        )
    stop = threading.Event()
    threads = [
        threading.Thread(target=r.run, args=(stop, coalesce), daemon=True)
        for r in reps
    ]
    node.start()
    for t in threads:
        t.start()
    ok = all(d.wait(timeout=deadline_s) for d in dones.values())
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    node.stop()
    if not ok:
        raise RuntimeError(
            f"stalled: heights {[len(c) for c in commits.values()]}"
            f" of {target}"
        )
    return commits


def commits_digest(commits_by_index: dict) -> str:
    """One digest over all local chains — the worker asserts local chains
    identical first, so the digest describes THE chain."""
    chains = [
        tuple(sorted(c.items())) for c in commits_by_index.values()
    ]
    assert all(c == chains[0] for c in chains), "local replicas diverged"
    return hashlib.sha256(repr(chains[0]).encode()).hexdigest()


def build_tpu_stacks(ring, collector: list):
    """The tpu-mode verification stack: ONE shared TpuWireVerifier
    (resident ValidatorTable, grouped challenge format) for the process,
    one DeviceTallyFlusher (n=1 device vote grid) per replica, every
    device-sourced count cross-checked via CheckedTallyView instances
    appended to ``collector``. Imports jax lazily — host mode must not
    pay for it."""
    from hyperdrive_tpu.ops.ed25519_wire import (
        TpuWireVerifier,
        ValidatorTable,
    )
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView
    from hyperdrive_tpu.tallyflush import DeviceTallyFlusher

    n = len(ring.signatories)
    table = ValidatorTable([ring[i].public for i in range(n)])
    # One 64-lane bucket: a 4-validator window never exceeds it, and on
    # the 1-core CI host every extra bucket is another multi-second
    # XLA compile (or AOT load) per worker process at warmup.
    wv = TpuWireVerifier(buckets=(64,), table=table, backend="xla")

    def check(view, proc):
        v = CheckedTallyView(view, proc)
        collector.append(v)
        return v

    def make_stack(i):
        fl = DeviceTallyFlusher(
            wv, list(ring.signatories), tally_check=check
        )
        # Compiles happen at boot, not inside the first consensus round
        # where they would read as network stalls and fire timeouts.
        fl.warmup()
        return wv, fl

    return wv, make_stack


def main() -> None:
    port_a, port_b, rank, target = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
        int(sys.argv[4]),
    )
    mode = sys.argv[5] if len(sys.argv) > 5 else "host"
    my_port = (port_a, port_b)[rank]
    peer_port = (port_a, port_b)[1 - rank]
    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    node = TcpNode(listen_port=my_port)
    node.add_peer("127.0.0.1", peer_port)
    indices = (0, 1) if rank == 0 else (2, 3)
    if mode == "tpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(
                    os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    ".jax_cache",
                ),
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0
            )
        except Exception:
            pass  # cache is an optimization, never a requirement
        views: list = []
        wv, make_stack = build_tpu_stacks(ring, views)
        commits = run_local_replicas(
            node, ring, indices, target, deadline_s=420.0, timeout_s=20.0,
            make_stack=make_stack, coalesce=True,
        )
        digest = commits_digest(commits)
        consulted = sum(v.hits for v in views)
        print(
            f"TRANSPORT_OK rank={rank} heights={target} digest={digest} "
            f"mode=tpu consulted={consulted} "
            f"grouped={wv.stats['lanes_grouped']}",
            flush=True,
        )
        return
    commits = run_local_replicas(node, ring, indices, target)
    digest = commits_digest(commits)
    print(
        f"TRANSPORT_OK rank={rank} heights={target} digest={digest}",
        flush=True,
    )


if __name__ == "__main__":
    main()
