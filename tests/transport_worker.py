"""Worker + shared builders for the loopback-TCP Broadcaster demo.

``python tests/transport_worker.py <portA> <portB> <rank> <target>``
hosts replicas {0,1} (rank 0) or {2,3} (rank 1) of a 4-validator network
on a :class:`hyperdrive_tpu.transport.TcpNode`, with real wall-clock
LinearTimer timeouts and signed messages verified per replica — consensus
across an OS process boundary with no shared memory. Prints
``TRANSPORT_OK rank=<r> heights=<target> digest=<sha256>`` where the
digest covers the (identical) commit chains of both local replicas; the
parent test asserts the digests agree ACROSS processes.

The builders are imported by tests/test_transport.py for the in-process
4-node variant; this module must not import jax (the transport layer is
pure host code, and worker startup stays fast).
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.testutil import (
    CommitterCallback,
    MockProposer,
    MockValidator,
)
from hyperdrive_tpu.timer import LinearTimer
from hyperdrive_tpu.transport import TcpBroadcaster, TcpNode
from hyperdrive_tpu.verifier import HostVerifier


def deterministic_value(height, round_):
    return hashlib.sha256(b"txval-%d-%d" % (height, round_)).digest()


def build_replica(node: TcpNode, ring: KeyRing, i: int, target: int,
                  commits: dict, done: threading.Event,
                  timeout_s: float = 5.0) -> Replica:
    """One threaded replica wired to the node: TcpBroadcaster (signing),
    LinearTimer (real wall-clock timeout threads), HostVerifier (every
    delivered message's signature checked), commit hook recording into
    ``commits`` and firing ``done`` at the target height."""
    cell: dict = {}
    timer = LinearTimer(
        handle_timeout_propose=lambda t: cell["r"].timeout(t),
        handle_timeout_prevote=lambda t: cell["r"].timeout(t),
        handle_timeout_precommit=lambda t: cell["r"].timeout(t),
        timeout=timeout_s,
    )

    def on_commit(height, value):
        commits[height] = value
        if len(commits) >= target:
            done.set()
        return 0, None

    rep = Replica(
        ReplicaOptions(),
        whoami=ring[i].public,
        signatories=list(ring.signatories),
        timer=timer,
        proposer=MockProposer(fn=deterministic_value),
        validator=MockValidator(ok=True),
        committer=CommitterCallback(on_commit=on_commit),
        catcher=None,
        broadcaster=TcpBroadcaster(node, keypair=ring[i]),
        verifier=HostVerifier(),
    )
    cell["r"] = rep
    node.add_replica(rep)
    return rep


def run_local_replicas(node: TcpNode, ring: KeyRing, indices, target: int,
                       deadline_s: float = 120.0):
    """Run the given replica indices on ``node`` until every one commits
    ``target`` heights (or the deadline passes). Returns {index: commits}.
    """
    commits = {i: {} for i in indices}
    dones = {i: threading.Event() for i in indices}
    reps = [
        build_replica(node, ring, i, target, commits[i], dones[i])
        for i in indices
    ]
    stop = threading.Event()
    threads = [
        threading.Thread(target=r.run, args=(stop,), daemon=True)
        for r in reps
    ]
    node.start()
    for t in threads:
        t.start()
    ok = all(d.wait(timeout=deadline_s) for d in dones.values())
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    node.stop()
    if not ok:
        raise RuntimeError(
            f"stalled: heights {[len(c) for c in commits.values()]}"
            f" of {target}"
        )
    return commits


def commits_digest(commits_by_index: dict) -> str:
    """One digest over all local chains — the worker asserts local chains
    identical first, so the digest describes THE chain."""
    chains = [
        tuple(sorted(c.items())) for c in commits_by_index.values()
    ]
    assert all(c == chains[0] for c in chains), "local replicas diverged"
    return hashlib.sha256(repr(chains[0]).encode()).hexdigest()


def main() -> None:
    port_a, port_b, rank, target = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
        int(sys.argv[4]),
    )
    my_port = (port_a, port_b)[rank]
    peer_port = (port_a, port_b)[1 - rank]
    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    node = TcpNode(listen_port=my_port)
    node.add_peer("127.0.0.1", peer_port)
    indices = (0, 1) if rank == 0 else (2, 3)
    commits = run_local_replicas(node, ring, indices, target)
    digest = commits_digest(commits)
    print(
        f"TRANSPORT_OK rank={rank} heights={target} digest={digest}",
        flush=True,
    )


if __name__ == "__main__":
    main()
