"""MessageQueue: ordering, whitelist, capacity, drop-below-height, windows.

Mirrors mq/mq_test.go's strategy.
"""

import random

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.mq import MessageQueue


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def pv(sender, h, r):
    return Prevote(height=h, round=r, value=b"\x01" * 32, sender=sender)


def collect(mq, height, allowed):
    got = []
    n = mq.consume(height, got.append, got.append, got.append, allowed)
    return got, n


def test_consume_in_height_round_order_per_sender(rng):
    mq = MessageQueue()
    coords = [(h, r) for h in range(1, 6) for r in range(4)]
    shuffled = coords[:]
    rng.shuffle(shuffled)
    for h, r in shuffled:
        mq.insert_prevote(pv(sig(1), h, r))
    got, n = collect(mq, 10, {sig(1)})
    assert n == len(coords)
    assert [(m.height, m.round) for m in got] == coords


def test_equal_keys_stay_fifo():
    mq = MessageQueue()
    a = Prevote(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    b = Prevote(height=1, round=0, value=b"\x02" * 32, sender=sig(1))
    c = Prevote(height=1, round=0, value=b"\x03" * 32, sender=sig(1))
    for m in (a, b, c):
        mq.insert_prevote(m)
    got, _ = collect(mq, 1, {sig(1)})
    assert got == [a, b, c]


def test_consume_respects_height_bound():
    mq = MessageQueue()
    for h in (1, 2, 3, 4):
        mq.insert_prevote(pv(sig(1), h, 0))
    got, n = collect(mq, 2, {sig(1)})
    assert [(m.height) for m in got] == [1, 2]
    assert n == 2
    got, n = collect(mq, 10, {sig(1)})
    assert [(m.height) for m in got] == [3, 4]


def test_whitelist_drops_but_counts():
    # Filtered messages are consumed (and counted) but not dispatched,
    # matching the reference's n++-before-filter behaviour (mq/mq.go:44-51).
    mq = MessageQueue()
    mq.insert_prevote(pv(sig(1), 1, 0))
    mq.insert_prevote(pv(sig(2), 1, 0))
    got, n = collect(mq, 1, {sig(1)})
    assert n == 2
    assert [m.sender for m in got] == [sig(1)]
    # Nothing left afterwards — the filtered message is gone.
    got, n = collect(mq, 10, {sig(1), sig(2)})
    assert n == 0 and got == []


def test_capacity_eviction_drops_far_future():
    mq = MessageQueue(max_capacity=3)
    for h in (5, 6, 7):
        mq.insert_prevote(pv(sig(1), h, 0))
    mq.insert_prevote(pv(sig(1), 1, 0))  # nearer message displaces the tail
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [1, 5, 6]


def test_capacity_one():
    mq = MessageQueue(max_capacity=1)
    mq.insert_prevote(pv(sig(1), 5, 0))
    mq.insert_prevote(pv(sig(1), 1, 0))
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [1]


def test_capacity_is_per_sender():
    mq = MessageQueue(max_capacity=2)
    for i in (1, 2, 3):
        mq.insert_prevote(pv(sig(i), 1, 0))
        mq.insert_prevote(pv(sig(i), 2, 0))
        mq.insert_prevote(pv(sig(i), 3, 0))  # evicted per sender
    assert len(mq) == 6


def test_drop_messages_below_height():
    mq = MessageQueue()
    for h in (1, 2, 3, 4):
        mq.insert_prevote(pv(sig(1), h, 0))
    mq.drop_messages_below_height(3)
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [3, 4]


def test_mixed_message_types_dispatch_correctly():
    mq = MessageQueue()
    p = Propose(height=1, round=0, valid_round=-1, value=b"\x01" * 32, sender=sig(1))
    v = Prevote(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    c = Precommit(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    mq.insert_precommit(c)
    mq.insert_prevote(v)
    mq.insert_propose(p)
    seen = {"p": [], "v": [], "c": []}
    mq.consume(1, seen["p"].append, seen["v"].append, seen["c"].append, {sig(1)})
    assert seen["p"] == [p] and seen["v"] == [v] and seen["c"] == [c]


def test_drain_window_caps_and_preserves_order():
    mq = MessageQueue()
    for h in range(1, 8):
        mq.insert_prevote(pv(sig(1), h, 0))
    window = mq.drain_window(height=5, window=3)
    assert [m.height for m in window] == [1, 2, 3]
    window = mq.drain_window(height=5, window=10)
    assert [m.height for m in window] == [4, 5]
    assert len(mq) == 2  # heights 6,7 remain


def test_drain_window_multiple_senders():
    mq = MessageQueue()
    for i in (1, 2, 3):
        mq.insert_prevote(pv(sig(i), 1, 0))
    window = mq.drain_window(height=1, window=10)
    assert len(window) == 3
    assert len(mq) == 0


def test_drain_window_global_hr_interleave(rng):
    """A multi-sender multi-height backlog drains in global ascending
    (height, round) order — not per-sender blocks."""
    mq = MessageQueue()
    coords = [(h, r) for h in range(1, 5) for r in range(3)]
    inserts = [(s, h, r) for s in (1, 2, 3, 4) for (h, r) in coords]
    rng.shuffle(inserts)
    for s, h, r in inserts:
        mq.insert_prevote(pv(sig(s), h, r))

    window = mq.drain_window(height=10, window=10_000)
    keys = [(m.height, m.round) for m in window]
    assert keys == sorted(keys)
    assert len(window) == len(inserts)
    # Every (h, r) key appears once per sender, grouped together.
    for h, r in coords:
        assert keys.count((h, r)) == 4


def test_drain_window_cap_takes_globally_smallest_keys():
    """When the window caps, the drained prefix is the globally smallest
    (h, r) keys — a later round can never jump ahead of an earlier one."""
    mq = MessageQueue()
    # Sender 1 holds early rounds, sender 2 holds later rounds.
    for r in (0, 1, 2):
        mq.insert_prevote(pv(sig(1), 1, r))
    for r in (3, 4, 5):
        mq.insert_prevote(pv(sig(2), 1, r))
    window = mq.drain_window(height=1, window=4)
    assert [(m.height, m.round) for m in window] == [(1, 0), (1, 1), (1, 2), (1, 3)]
    # The remainder is intact and drains next.
    window = mq.drain_window(height=1, window=4)
    assert [(m.height, m.round) for m in window] == [(1, 4), (1, 5)]


def test_drain_window_fifo_within_equal_keys():
    """Equal (h, r) keys from one sender stay FIFO through the merge."""
    mq = MessageQueue()
    a = Prevote(height=1, round=0, value=b"\x0a" * 32, sender=sig(1))
    b = Prevote(height=1, round=0, value=b"\x0b" * 32, sender=sig(1))
    mq.insert_prevote(a)
    mq.insert_prevote(b)
    window = mq.drain_window(height=1, window=10)
    assert window == [a, b]


def test_drain_window_matches_consume_key_order(rng):
    """The window's (h, r) key sequence equals the sorted key sequence a
    consume drain dispatches — batching must not reorder keys."""
    mq1, mq2 = MessageQueue(), MessageQueue()
    inserts = []
    for s in range(1, 6):
        for _ in range(20):
            inserts.append((s, rng.randrange(1, 4), rng.randrange(0, 5)))
    rng.shuffle(inserts)
    for s, h, r in inserts:
        m = pv(sig(s), h, r)
        mq1.insert_prevote(m)
        mq2.insert_prevote(m)

    window = mq1.drain_window(height=3, window=10_000)
    got, _ = collect(mq2, 3, {sig(s) for s in range(1, 6)})
    assert sorted((m.height, m.round) for m in got) == [
        (m.height, m.round) for m in window
    ]


# Reference: mq_test.go:118-333 — whitelist accept/reject incl. dynamic
# add/remove between consume calls.


def test_whitelist_is_per_consume_call():
    mq = MessageQueue()
    mq.insert_prevote(pv(sig(1), 1, 0))
    mq.insert_prevote(pv(sig(2), 1, 0))
    got, n = collect(mq, 1, {sig(1)})
    # Both messages consumed (the count includes whitelist drops,
    # reference mq.go:36-66), only sig(1)'s dispatched.
    assert n == 2
    assert [m.sender for m in got] == [sig(1)]

    # A sender added to the whitelist later gets its NEW messages through;
    # the earlier one is gone (consumed-and-dropped, not quarantined).
    mq.insert_prevote(pv(sig(2), 2, 0))
    got, n = collect(mq, 2, {sig(1), sig(2)})
    assert [m.sender for m in got] == [sig(2)]

    # And a sender removed from the whitelist is dropped again.
    mq.insert_prevote(pv(sig(1), 3, 0))
    got, n = collect(mq, 3, set())
    assert got == [] and n == 1


def test_capacity_one_keeps_earliest_key():
    # Reference: mq_test.go:641-795 capacity-1 eviction: the far-future
    # tail is dropped, the smallest (height, round) survives.
    mq = MessageQueue(max_capacity=1)
    mq.insert_prevote(pv(sig(1), 5, 0))
    mq.insert_prevote(pv(sig(1), 2, 0))  # smaller key evicts the tail
    mq.insert_prevote(pv(sig(1), 9, 0))  # over capacity: dropped
    got, n = collect(mq, 10, {sig(1)})
    assert [(m.height, m.round) for m in got] == [(2, 0)]


def test_capacity_is_per_sender_not_global():
    mq = MessageQueue(max_capacity=2)
    for h in range(1, 6):
        mq.insert_prevote(pv(sig(1), h, 0))
        mq.insert_prevote(pv(sig(2), h, 0))
    got, _ = collect(mq, 10, {sig(1), sig(2)})
    assert len(got) == 4  # 2 per sender
    assert {m.sender for m in got} == {sig(1), sig(2)}


def test_drop_below_height_keeps_exact_boundary():
    mq = MessageQueue()
    for h in (1, 2, 3, 4):
        mq.insert_prevote(pv(sig(1), h, 0))
    mq.drop_messages_below_height(3)
    got, _ = collect(mq, 10, {sig(1)})
    assert [m.height for m in got] == [3, 4]  # height 3 itself survives


def test_drain_all_leaves_future_heights_buffered():
    mq = MessageQueue()
    mq.insert_prevote(pv(sig(1), 1, 2))
    mq.insert_prevote(pv(sig(1), 3, 0))
    mq.insert_prevote(pv(sig(2), 1, 0))
    window = mq.drain_all(1)
    assert [(m.height, m.round) for m in window] == [(1, 0), (1, 2)]
    assert len(mq) == 1  # the height-3 message stays
    window = mq.drain_all(3)
    assert [(m.height, m.round) for m in window] == [(3, 0)]


def test_drain_all_matches_drain_window_order(rng):
    # The uncapped scan+sort drain and the k-way heap merge must produce
    # the IDENTICAL sequence for any backlog.
    mq1, mq2 = MessageQueue(), MessageQueue()
    msgs = []
    for _i in range(200):
        m = pv(sig(rng.randint(1, 9)), rng.randint(1, 4), rng.randint(0, 3))
        msgs.append(m)
    for m in msgs:
        mq1.insert_prevote(m)
        mq2.insert_prevote(m)
    a = mq1.drain_all(3)
    b = mq2.drain_window(3, 10_000)
    assert a == b
    assert [(m.height, m.round) for m in a] == sorted(
        (m.height, m.round) for m in a
    )
