"""MessageQueue: ordering, whitelist, capacity, drop-below-height, windows.

Mirrors mq/mq_test.go's strategy.
"""

import random

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.mq import MessageQueue


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def pv(sender, h, r):
    return Prevote(height=h, round=r, value=b"\x01" * 32, sender=sender)


def collect(mq, height, allowed):
    got = []
    n = mq.consume(height, got.append, got.append, got.append, allowed)
    return got, n


def test_consume_in_height_round_order_per_sender(rng):
    mq = MessageQueue()
    coords = [(h, r) for h in range(1, 6) for r in range(4)]
    shuffled = coords[:]
    rng.shuffle(shuffled)
    for h, r in shuffled:
        mq.insert_prevote(pv(sig(1), h, r))
    got, n = collect(mq, 10, {sig(1)})
    assert n == len(coords)
    assert [(m.height, m.round) for m in got] == coords


def test_equal_keys_stay_fifo():
    mq = MessageQueue()
    a = Prevote(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    b = Prevote(height=1, round=0, value=b"\x02" * 32, sender=sig(1))
    c = Prevote(height=1, round=0, value=b"\x03" * 32, sender=sig(1))
    for m in (a, b, c):
        mq.insert_prevote(m)
    got, _ = collect(mq, 1, {sig(1)})
    assert got == [a, b, c]


def test_consume_respects_height_bound():
    mq = MessageQueue()
    for h in (1, 2, 3, 4):
        mq.insert_prevote(pv(sig(1), h, 0))
    got, n = collect(mq, 2, {sig(1)})
    assert [(m.height) for m in got] == [1, 2]
    assert n == 2
    got, n = collect(mq, 10, {sig(1)})
    assert [(m.height) for m in got] == [3, 4]


def test_whitelist_drops_but_counts():
    # Filtered messages are consumed (and counted) but not dispatched,
    # matching the reference's n++-before-filter behaviour (mq/mq.go:44-51).
    mq = MessageQueue()
    mq.insert_prevote(pv(sig(1), 1, 0))
    mq.insert_prevote(pv(sig(2), 1, 0))
    got, n = collect(mq, 1, {sig(1)})
    assert n == 2
    assert [m.sender for m in got] == [sig(1)]
    # Nothing left afterwards — the filtered message is gone.
    got, n = collect(mq, 10, {sig(1), sig(2)})
    assert n == 0 and got == []


def test_capacity_eviction_drops_far_future():
    mq = MessageQueue(max_capacity=3)
    for h in (5, 6, 7):
        mq.insert_prevote(pv(sig(1), h, 0))
    mq.insert_prevote(pv(sig(1), 1, 0))  # nearer message displaces the tail
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [1, 5, 6]


def test_capacity_one():
    mq = MessageQueue(max_capacity=1)
    mq.insert_prevote(pv(sig(1), 5, 0))
    mq.insert_prevote(pv(sig(1), 1, 0))
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [1]


def test_capacity_is_per_sender():
    mq = MessageQueue(max_capacity=2)
    for i in (1, 2, 3):
        mq.insert_prevote(pv(sig(i), 1, 0))
        mq.insert_prevote(pv(sig(i), 2, 0))
        mq.insert_prevote(pv(sig(i), 3, 0))  # evicted per sender
    assert len(mq) == 6


def test_drop_messages_below_height():
    mq = MessageQueue()
    for h in (1, 2, 3, 4):
        mq.insert_prevote(pv(sig(1), h, 0))
    mq.drop_messages_below_height(3)
    got, _ = collect(mq, 100, {sig(1)})
    assert [m.height for m in got] == [3, 4]


def test_mixed_message_types_dispatch_correctly():
    mq = MessageQueue()
    p = Propose(height=1, round=0, valid_round=-1, value=b"\x01" * 32, sender=sig(1))
    v = Prevote(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    c = Precommit(height=1, round=0, value=b"\x01" * 32, sender=sig(1))
    mq.insert_precommit(c)
    mq.insert_prevote(v)
    mq.insert_propose(p)
    seen = {"p": [], "v": [], "c": []}
    mq.consume(1, seen["p"].append, seen["v"].append, seen["c"].append, {sig(1)})
    assert seen["p"] == [p] and seen["v"] == [v] and seen["c"] == [c]


def test_drain_window_caps_and_preserves_order():
    mq = MessageQueue()
    for h in range(1, 8):
        mq.insert_prevote(pv(sig(1), h, 0))
    window = mq.drain_window(height=5, window=3)
    assert [m.height for m in window] == [1, 2, 3]
    window = mq.drain_window(height=5, window=10)
    assert [m.height for m in window] == [4, 5]
    assert len(mq) == 2  # heights 6,7 remain


def test_drain_window_multiple_senders():
    mq = MessageQueue()
    for i in (1, 2, 3):
        mq.insert_prevote(pv(sig(i), 1, 0))
    window = mq.drain_window(height=1, window=10)
    assert len(window) == 3
    assert len(mq) == 0
