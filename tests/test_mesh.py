"""Sharded verify+tally over a virtual 8-device CPU mesh.

conftest forces --xla_force_host_platform_device_count=8, so shard_map
compiles and executes real collectives (psum over the validator axis)
without TPU hardware — the same code path the multi-chip dry run uses.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.ops.tally import pack_values
from hyperdrive_tpu.parallel import (
    grid_pack,
    make_mesh,
    make_sharded_step,
    sharded_verify_tally,
)


def test_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.requires_shard_map
def test_sharded_step_matches_host():
    mesh = make_mesh(hr=2, val=4)
    step = sharded_verify_tally(mesh)

    R, V = 2, 4
    ring = KeyRing.deterministic(V, namespace=b"mesh")
    values = [bytes([r + 1]) * 32 for r in range(R)]
    corrupt = {(0, 2), (1, 0)}
    shaped, prevalid = grid_pack(ring, R, V, values, corrupt=corrupt)

    vote_vals = jnp.asarray(
        np.stack([pack_values([values[r]] * V) for r in range(R)])
    )
    target_vals = jnp.asarray(pack_values(values))
    f = jnp.int32(V // 3)

    counts, flags, ok = step(*shaped, vote_vals, target_vals, f)

    ok_np = np.asarray(ok)
    for r in range(R):
        for v in range(V):
            assert ok_np[r, v] == ((r, v) not in corrupt)
    for r in range(R):
        expect = V - sum(1 for (rr, _) in corrupt if rr == r)
        assert int(np.asarray(counts["matching"])[r]) == expect
        assert int(np.asarray(counts["total"])[r]) == expect
        # 2f+1 = 3: both rounds still have exactly 3 valid votes -> quorum.
        assert bool(np.asarray(flags["quorum_matching"])[r])


@pytest.mark.requires_shard_map
def test_sharded_chalwire_matches_packed_step():
    """The 68 B/lane challenge pipeline over the mesh: same signatures
    through sharded_chalwire_tally (device SHA-512 + mod-L + ladder,
    lanes sharded, table replicated, psum over 'val') and through the
    packed sharded step — identical verification masks, counts, and
    flags; corrupt lanes reject on the right (r, v)."""
    from hyperdrive_tpu.parallel import (
        grid_pack_wire,
        sharded_chalwire_tally,
    )

    mesh = make_mesh(hr=2, val=4)
    R, V = 2, 4
    ring = KeyRing.deterministic(V, namespace=b"meshchal")
    values = [bytes([r + 1]) * 32 for r in range(R)]
    corrupt = {(0, 2), (1, 0)}
    (idx, r_rows, s_rows, m_round), table, prevalid = grid_pack_wire(
        ring, R, V, values, corrupt=corrupt
    )
    assert bool(prevalid.all())  # corruption breaks verification, not parse

    vote_vals = jnp.asarray(
        np.stack([pack_values([values[r]] * V) for r in range(R)])
    )
    target_vals = jnp.asarray(pack_values(values))
    f = jnp.int32(V // 3)

    step = sharded_chalwire_tally(mesh)
    counts, flags, ok = step(
        idx, r_rows, s_rows, m_round, *[
            jnp.asarray(a) for a in table.arrays_chal()
        ], vote_vals, target_vals, f
    )
    ok_np = np.asarray(ok)
    for r in range(R):
        for v in range(V):
            assert ok_np[r, v] == ((r, v) not in corrupt), (r, v)

    # Differential vs an actual RUN of the packed sharded step on the
    # same corrupt pattern (signatures differ — grid_pack signs its own
    # digest convention — but the verdict mask, counts, and flags must be
    # identical; a bug shared by both steps' common tail still has the
    # mask assertions above to answer to).
    pshaped, pprevalid = grid_pack(ring, R, V, values, corrupt=corrupt)
    assert bool(pprevalid.all())
    pcounts, pflags, pok = sharded_verify_tally(mesh)(
        *pshaped, vote_vals, target_vals, f
    )
    np.testing.assert_array_equal(ok_np, np.asarray(pok))
    for key in counts:
        np.testing.assert_array_equal(
            np.asarray(counts[key]), np.asarray(pcounts[key]), err_msg=key
        )
    for key in flags:
        np.testing.assert_array_equal(
            np.asarray(flags[key]), np.asarray(pflags[key]), err_msg=key
        )


@pytest.mark.requires_shard_map
def test_1d_and_2d_meshes():
    for hr, val in ((1, 8), (2, 4), (4, 2)):
        mesh = make_mesh(hr=hr, val=val)
        step, example_args = make_sharded_step(mesh)
        args = example_args(rounds=hr * 2, validators=val * 2)
        counts, flags, ok = step(*args)
        # All-zero signatures never verify: zero counts everywhere.
        assert int(np.asarray(counts["total"]).sum()) == 0
        assert not bool(np.asarray(flags["quorum_any"]).any())


def test_mesh_shape_validation():
    with pytest.raises(ValueError):
        make_mesh(hr=3)  # 3 does not divide 8


@pytest.mark.requires_shard_map
def test_dryrun_multichip_is_self_checking():
    """The driver's dry run verifies real signatures and exact psum'd
    tallies — it must pass on the virtual 8-device mesh, and its internal
    assertions are the correctness certificate."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
