"""Sharded verify+tally over a virtual 8-device CPU mesh.

conftest forces --xla_force_host_platform_device_count=8, so shard_map
compiles and executes real collectives (psum over the validator axis)
without TPU hardware — the same code path the multi-chip dry run uses.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost
from hyperdrive_tpu.ops.tally import pack_values
from hyperdrive_tpu.parallel import make_mesh, make_sharded_step, sharded_verify_tally


def grid_pack(ring, rounds, validators, values, corrupt=()):
    """Sign one vote per (round, validator) and pack to [R, V, ...] arrays.

    values: list of 32-byte proposal values per round. corrupt: set of
    (r, v) whose signature byte 0 is flipped.
    """
    host = Ed25519BatchHost(buckets=(rounds * validators,))
    items = []
    for r in range(rounds):
        for v in range(validators):
            kp = ring[v]
            digest = values[r] + bytes([r])
            sig = host_ed.sign(kp.seed, digest)
            if (r, v) in corrupt:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            items.append((kp.public, digest, sig))
    arrays, prevalid, n = host.pack(items)
    assert n == rounds * validators
    shaped = tuple(
        jnp.asarray(a).reshape(rounds, validators, *a.shape[1:]) for a in arrays
    )
    return shaped, prevalid.reshape(rounds, validators)


def test_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_step_matches_host():
    mesh = make_mesh(hr=2, val=4)
    step = sharded_verify_tally(mesh)

    R, V = 2, 4
    ring = KeyRing.deterministic(V, namespace=b"mesh")
    values = [bytes([r + 1]) * 32 for r in range(R)]
    corrupt = {(0, 2), (1, 0)}
    shaped, prevalid = grid_pack(ring, R, V, values, corrupt=corrupt)

    vote_vals = jnp.asarray(
        np.stack([pack_values([values[r]] * V) for r in range(R)])
    )
    target_vals = jnp.asarray(pack_values(values))
    f = jnp.int32(V // 3)

    counts, flags, ok = step(*shaped, vote_vals, target_vals, f)

    ok_np = np.asarray(ok)
    for r in range(R):
        for v in range(V):
            assert ok_np[r, v] == ((r, v) not in corrupt)
    for r in range(R):
        expect = V - sum(1 for (rr, _) in corrupt if rr == r)
        assert int(np.asarray(counts["matching"])[r]) == expect
        assert int(np.asarray(counts["total"])[r]) == expect
        # 2f+1 = 3: both rounds still have exactly 3 valid votes -> quorum.
        assert bool(np.asarray(flags["quorum_matching"])[r])


def test_1d_and_2d_meshes():
    for hr, val in ((1, 8), (2, 4), (4, 2)):
        mesh = make_mesh(hr=hr, val=val)
        step, example_args = make_sharded_step(mesh)
        args = example_args(rounds=hr * 2, validators=val * 2)
        counts, flags, ok = step(*args)
        # All-zero signatures never verify: zero counts everywhere.
        assert int(np.asarray(counts["total"]).sum()) == 0
        assert not bool(np.asarray(flags["quorum_any"]).any())


def test_mesh_shape_validation():
    with pytest.raises(ValueError):
        make_mesh(hr=3)  # 3 does not divide 8
