"""Differential tests for the Pallas verify ladder (interpret mode).

The kernel must agree bit-for-bit with BOTH the host oracle and the XLA
kernel on every lane class: valid signatures, corrupted scalars, wrong
digests, and the zero-padded lanes the packer emits for malformed inputs.
Interpret mode runs the real kernel logic (including the scratch-table
build and signed recoding) on CPU; the TPU measurements live in bench.py.
"""

import numpy as np

import jax.numpy as jnp

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost, make_verify_fn
from hyperdrive_tpu.ops.ed25519_pallas import verify_pallas

BLOCK = 64  # small block: interpret-mode cost scales with padded size


def _pack(items):
    host = Ed25519BatchHost(buckets=(len(items),))
    arrays, prevalid, n = host.pack(items)
    return tuple(jnp.asarray(a) for a in arrays), prevalid, n


def _host_verdicts(items):
    return np.array(
        [host_ed.verify(pub, digest, sig) for pub, digest, sig in items]
    )


def build_mixed(n=BLOCK, seed=7):
    """n lanes covering every verdict class."""
    rng = np.random.default_rng(seed)
    ring = KeyRing.deterministic(16, namespace=b"pl-test")
    items = []
    for i in range(n):
        kp = ring[i % 16]
        digest = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        sig = host_ed.sign(kp.seed, digest)
        kind = i % 4
        if kind == 1:  # corrupted s scalar bit
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
        elif kind == 2:  # signature over a different digest
            digest = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        elif kind == 3 and i % 8 == 3:  # malformed point -> prevalid False
            sig = b"\xff" * 64
        items.append((kp.public, digest, sig))
    return items


def test_pallas_matches_host_oracle_and_xla_kernel():
    items = build_mixed()
    arrays, prevalid, n = _pack(items)

    got = np.asarray(
        verify_pallas(*arrays, block=BLOCK, interpret=True)
    ) & prevalid
    want = _host_verdicts(items)
    np.testing.assert_array_equal(got[:n], want)

    xla = np.asarray(make_verify_fn()(*arrays)) & prevalid
    np.testing.assert_array_equal(got, xla)


def test_pallas_pads_partial_blocks():
    items = build_mixed(n=40, seed=11)  # 40 -> padded to 64
    arrays, prevalid, n = _pack(items)
    assert arrays[0].shape[0] == 40
    got = np.asarray(
        verify_pallas(*arrays, block=BLOCK, interpret=True)
    ) & prevalid
    assert got.shape == (40,)
    np.testing.assert_array_equal(got[:n], _host_verdicts(items))


def test_pallas_rejects_all_zero_lanes():
    z20 = jnp.zeros((BLOCK, 20), dtype=jnp.int32)
    z64 = jnp.zeros((BLOCK, 64), dtype=jnp.int32)
    got = np.asarray(
        verify_pallas(z20, z20, z20, z20, z20, z64, z64,
                      block=BLOCK, interpret=True)
    )
    assert not got.any()
