"""Test configuration.

JAX-based tests run on a virtual 8-device CPU platform so multi-chip
sharding paths compile and execute without TPU hardware. The env vars must
be set before the first ``import jax`` anywhere in the test process.
"""

import os
import random

import pytest

# The container exports JAX_PLATFORMS=axon and a sitecustomize that
# re-registers the TPU plugin, so env vars alone don't stick — force the
# platform through jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


@pytest.fixture
def rng() -> random.Random:
    """Seeded RNG; override the seed with HYPERDRIVE_TEST_SEED for replay."""
    seed = int(os.environ.get("HYPERDRIVE_TEST_SEED", "1337"))
    return random.Random(seed)
