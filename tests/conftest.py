"""Test configuration.

JAX-based tests run on a virtual 8-device CPU platform so multi-chip
sharding paths compile and execute without TPU hardware. The env vars must
be set before the first ``import jax`` anywhere in the test process.
"""

import os
import random

import pytest

# Consensus sanitizer (ANALYSIS.md): tier-1 runs with the HDS invariant
# checks active unless the caller opted out explicitly (HD_SANITIZE=0).
os.environ.setdefault("HD_SANITIZE", "1")

# The container exports JAX_PLATFORMS=axon and a sitecustomize that
# re-registers the TPU plugin, so env vars alone don't stick — force the
# platform through jax.config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.5: first-class CPU device-count option. Older jaxlibs get
    # the device count from the XLA_FLAGS fallback exported above.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

# Persistent compilation cache: the crypto kernels are large elementwise
# graphs (the fe25519 ladder, the unrolled SHA-512) that cost tens of
# seconds each to compile on this 1-core host; caching them across test
# runs turns repeat suite runs from compile-bound into run-bound. Keyed
# on backend + jaxlib version + HLO, so it never masks a code change.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "HD_JAX_CACHE",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

# Capability probes: environment-blocked features, not code defects.
# Tests that need them carry `requires_shard_map` / `requires_multiprocess`
# markers and are skipped WITH A REASON when the probe fails, so tier-1
# output separates "this build can't run it" from "this code is broken".
#
# - shard_map: the sharded verify/tally paths call the first-class
#   ``jax.shard_map`` API; older jax builds only ship the
#   ``jax.experimental`` spelling and fail with AttributeError.
# - multiprocess: the two-process distributed tests need a jaxlib whose
#   CPU backend can host cross-process collectives; builds without the
#   distributed runtime raise XlaRuntimeError at
#   ``jax.distributed.initialize``.
HAS_SHARD_MAP = hasattr(jax, "shard_map")
HAS_MULTIPROCESS = HAS_SHARD_MAP and jax.__version_info__ >= (0, 5)


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'` under a hard wall-clock cap (ROADMAP):
    # tests carrying this marker are the launch/compile-heavy ones whose
    # differential coverage is duplicated by a fresh-process CI smoke
    # (`python -m hyperdrive_tpu.ops msm-parity`, devsched parity) and
    # which the in-suite 8-virtual-device/1-core environment slows 5-10x
    # over their standalone cost. They still run in an unfiltered pass.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run"
    )
    config.addinivalue_line(
        "markers",
        "requires_shard_map: needs the first-class jax.shard_map API",
    )
    config.addinivalue_line(
        "markers",
        "requires_multiprocess: needs a multiprocess-collective jaxlib",
    )
    # Stdlib line-coverage measurement (no pytest-cov in the build
    # image) — see tests/_linecov.py. Opt-in: HD_LINECOV=1.
    if os.environ.get("HD_LINECOV"):
        import _linecov

        _linecov.start()


def pytest_collection_modifyitems(config, items):
    skip_sm = pytest.mark.skip(
        reason="this jax build has no first-class jax.shard_map "
        f"(jax {jax.__version__})"
    )
    skip_mp = pytest.mark.skip(
        reason="this jaxlib has no multiprocess collective runtime "
        f"(jax {jax.__version__})"
    )
    for item in items:
        if not HAS_SHARD_MAP and "requires_shard_map" in item.keywords:
            item.add_marker(skip_sm)
        if not HAS_MULTIPROCESS and "requires_multiprocess" in item.keywords:
            item.add_marker(skip_mp)


def pytest_sessionfinish(session, exitstatus):
    # The coverage gate (HD_LINECOV_MIN): measured by the SAME tool that
    # produced the published number, so a regression fails the run.
    if os.environ.get("HD_LINECOV") and exitstatus == 0:
        import _linecov

        if not _linecov.gate_ok():
            session.exitstatus = 1


def pytest_terminal_summary(terminalreporter):
    if os.environ.get("HD_LINECOV"):
        import _linecov

        _linecov.report(terminalreporter.write_line)


@pytest.fixture
def rng() -> random.Random:
    """Seeded RNG; override the seed with HYPERDRIVE_TEST_SEED for replay."""
    seed = int(os.environ.get("HYPERDRIVE_TEST_SEED", "1337"))
    return random.Random(seed)
