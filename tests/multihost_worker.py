"""Worker process for the 2-process jax.distributed test.

Launched by ``tests/test_multihost.py::test_two_process_distributed_step``
as ``python tests/multihost_worker.py <coordinator> <num_procs> <rank>``
with a 2-local-CPU-device platform, so the pod topology is 2 processes x
2 devices = 4 global devices. Each process drives the REAL multi-process
branches of hyperdrive_tpu.parallel.multihost — hybrid DCN x ICI mesh
construction, host-local-to-global window assembly, broadcast
replication — through the sharded verify+tally step, and checks its own
round's psum'd counts. Prints "MULTIHOST_OK rank=<r> ..." on success;
any failure raises (nonzero exit), which the parent asserts on.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, num_procs, rank = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )

    from hyperdrive_tpu.parallel import init_distributed

    # The REAL initialize path (multihost.py) — before any other JAX API.
    n_procs = init_distributed(
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=rank,
    )
    assert n_procs == num_procs, f"process_count {n_procs} != {num_procs}"

    import numpy as np

    import jax

    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.ops.tally import pack_values
    from hyperdrive_tpu.parallel import (
        global_window_from_local,
        make_hybrid_mesh,
        replicate_to_all_hosts,
        sharded_verify_tally,
    )

    assert jax.process_count() == num_procs
    n_global = len(jax.devices())
    assert n_global == 2 * num_procs, f"global devices {n_global}"

    # Hybrid mesh: 'hr' spans DCN (one row per process), 'val' stays on
    # the process-local devices — the multi-process branch of
    # make_hybrid_mesh (mesh_utils.create_hybrid_device_mesh).
    mesh = make_hybrid_mesh()
    assert mesh.axis_names == ("hr", "val")
    assert mesh.devices.shape == (num_procs, 2)

    # Every process derives the same deterministic votes; each packs only
    # ITS round's slab (host-side packing parallelizes across the pod) and
    # global_window_from_local assembles the global arrays without moving
    # data between hosts.
    R, V = num_procs, 2
    f = V // 3  # 0 — quorum 1; every uncorrupted round reaches it
    ring = KeyRing.deterministic(V, namespace=b"mh2p")
    values = [bytes([r + 9]) * 32 for r in range(R)]
    corrupt = {(1, 1)}  # round 1 loses one signature

    from hyperdrive_tpu.parallel import grid_pack

    shaped, prevalid = grid_pack(ring, R, V, values, corrupt=corrupt)
    assert bool(prevalid.all())
    local_slab = tuple(np.asarray(a)[rank : rank + 1] for a in shaped)
    window = global_window_from_local(mesh, local_slab)

    vote_local = np.stack([pack_values([values[rank]] * V)])
    (vote_vals,) = global_window_from_local(mesh, (vote_local,))
    target_local = pack_values([values[rank]])
    from jax.sharding import PartitionSpec as P

    (target_vals,) = global_window_from_local(
        mesh, (target_local,), spec=P("hr")
    )
    # The broadcast-replication branch (broadcast_one_to_all).
    f_arr = replicate_to_all_hosts(mesh, np.int32(f))

    step = sharded_verify_tally(mesh)
    counts, flags, ok = step(*window, vote_vals, target_vals, f_arr)

    # counts are sharded over 'hr': this process's addressable shard IS
    # its own round's psum-combined result.
    my_matching = int(np.asarray(counts["matching"].addressable_shards[0].data)[0])
    expect = V - sum(1 for (r, _) in corrupt if r == rank)
    assert my_matching == expect, (
        f"rank {rank}: matching {my_matching} != {expect}"
    )
    # ok is sharded (hr, val): this process holds one [1, 1] shard per
    # local device; reassemble its row from the shard indices.
    my_ok = {}
    for s in ok.addressable_shards:
        r0 = s.index[0].start or 0
        v0 = s.index[1].start or 0
        if r0 == rank:
            my_ok[v0] = bool(np.asarray(s.data)[0, 0])
    assert len(my_ok) == V, f"rank {rank}: missing ok shards ({my_ok})"
    for v in range(V):
        assert my_ok[v] == ((rank, v) not in corrupt), (
            f"rank {rank}: verify mask wrong at validator {v}"
        )

    print(
        f"MULTIHOST_OK rank={rank} procs={jax.process_count()} "
        f"devices={n_global} matching={my_matching}",
        flush=True,
    )

    # ---- Phase 2: full sharded CONSENSUS across the pod (the round-3
    # verdict's missing integration): every process runs the identical
    # deterministic 4-replica network; the vote grid's validator axis is
    # sharded over ALL FOUR global devices (val spans the process
    # boundary, so every settle's psum'd quorum counts are a real
    # cross-process collective), CheckedTallyView asserts device == host
    # count-for-count on every consulted query, and the commit maps are
    # proven byte-identical ACROSS PROCESSES by all-gathering their hash.
    import hashlib

    from jax.experimental import multihost_utils

    from hyperdrive_tpu.harness import Simulation
    from hyperdrive_tpu.ops.votegrid import CheckedTallyView
    from hyperdrive_tpu.parallel import make_mesh

    gmesh = make_mesh(devices=jax.devices(), hr=1)  # (1, 4): val x-process
    views = []

    def check(view, proc):
        v = CheckedTallyView(view, proc)
        views.append(v)
        return v

    kw = dict(n=4, target_height=3, seed=311, sign=True, burst=True)
    sharded = Simulation(
        **kw, device_tally=True, tally_mesh=gmesh, tally_check=check
    ).run(max_steps=200_000)
    assert sharded.completed, f"rank {rank}: stalled at {sharded.heights}"
    sharded.assert_safety()
    consulted = sum(v.hits for v in views)
    assert consulted > 0, f"rank {rank}: sharded counts never consulted"

    host_run = Simulation(**kw).run(max_steps=200_000)
    assert sharded.commits == host_run.commits, (
        f"rank {rank}: sharded consensus diverged from the host-tally run"
    )

    digest = hashlib.sha256(repr(sharded.commits).encode()).digest()
    gathered = multihost_utils.process_allgather(
        np.frombuffer(digest, dtype=np.uint8)
    )
    assert gathered.shape[0] == num_procs
    assert (gathered == gathered[0]).all(), (
        f"rank {rank}: commit maps differ across processes"
    )

    print(
        f"MULTIHOST_CONSENSUS_OK rank={rank} heights=3 "
        f"consulted={consulted} commits_hash_match=True",
        flush=True,
    )


if __name__ == "__main__":
    main()
