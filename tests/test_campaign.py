"""Adversarial economy: seeded attack campaigns as workloads.

Unit layer: the CampaignConfig wire roundtrip, the CampaignRecord
codec (magic/version/digest cross-checks, tamper rejection), and the
chaos monitor's campaign checks against hand-built summaries.

Integration layer: all three families at test scale through
``run_campaign`` — zero violations, replay digest identity, the
reputation loop's post-verify cost cut vs the no-reputation control,
and the CLI's run/replay surface.
"""

import dataclasses

import pytest

from hyperdrive_tpu.campaign import FAMILIES, CampaignConfig
from hyperdrive_tpu.campaign.record import CampaignRecord, summary_digest
from hyperdrive_tpu.campaign.runner import replay_campaign, run_campaign
from hyperdrive_tpu.chaos.monitor import InvariantMonitor, InvariantViolation
from hyperdrive_tpu.codec import SerdeError


def _cfg(family="storm", **kw):
    base = dict(
        family=family,
        seed=7,
        validators=64,
        committee_size=16,
        epochs=4,
        epoch_length=2,
        attackers=4,
        waves=3,
        wave_votes=2,
        attack_rate=4,
        sybils=8,
        budget_milli=200,
        grind_width=2,
    )
    base.update(kw)
    return CampaignConfig(**base)


# ------------------------------------------------------------------ config


def test_config_int_roundtrip_all_families():
    for family in FAMILIES:
        cfg = _cfg(family, seed=11, reputation=(family != "capture"))
        assert CampaignConfig.from_ints(cfg.as_ints()) == cfg
    # Trailing ints from a future config version are ignored, not fatal.
    cfg = _cfg()
    assert CampaignConfig.from_ints(cfg.as_ints() + (99, 99)) == cfg


def test_config_validate_rejects_degenerate_shapes():
    with pytest.raises(ValueError):
        _cfg(attackers=16).validate()  # no honest signer left
    with pytest.raises(ValueError):
        _cfg(sybils=40).validate()  # sybil majority
    with pytest.raises(ValueError):
        _cfg(budget_milli=400).validate()  # above the 1/3 stake budget
    with pytest.raises(ValueError):
        dataclasses.replace(_cfg(), family="meteor").validate()


# ------------------------------------------------------------------ record


def _marshal(rec):
    from hyperdrive_tpu.codec import Writer

    w = Writer(rem=1 << 20)
    rec.marshal(w)
    return w.data()


def test_record_roundtrip_and_file(tmp_path):
    summary = {"family": "storm", "waves": [{"wave": 0, "failed_rows": 3}]}
    rec = CampaignRecord.capture(_cfg(), summary)
    assert rec.digest == summary_digest(summary)
    assert CampaignRecord.load(_marshal(rec)) == rec
    path = tmp_path / "storm.bin"
    rec.dump(str(path))
    assert CampaignRecord.load_file(str(path)) == rec


def test_record_rejects_tampered_summary():
    rec = CampaignRecord.capture(_cfg(), {"family": "storm", "n": 1})
    blob = bytearray(_marshal(rec))
    # Flip a byte inside the JSON tail: digest cross-check must fire.
    blob[-2] ^= 0x01
    with pytest.raises(SerdeError):
        CampaignRecord.load(bytes(blob))


# ----------------------------------------------------------- monitor checks


def test_monitor_proportionality_bound_triggers_and_passes():
    row = dict(seats=3, committee=16, adv_stake=200, total_stake=1000)
    InvariantMonitor.check_campaign_proportionality(
        [row] * 8, grind_width=4
    )
    greedy = dict(row, seats=16)  # whole committee every epoch
    with pytest.raises(InvariantViolation) as err:
        InvariantMonitor.check_campaign_proportionality(
            [greedy] * 8, grind_width=4
        )
    assert err.value.kind == "capture-proportionality"


def test_monitor_storm_hygiene_catches_misattribution():
    summary = {
        "reputation": False,
        "honest": ["aaaa"],
        "attackers": ["bbbb"],
        "honest_rows": 2,
        "waves": [{"attacker_rows_verified": 0, "admitted": 2}],
        "gate": {
            "shed": {},
            "verify_failed": {"aaaa": 4},  # honest signer charged
            "demoted": [],
            "demotions": 0,
        },
    }
    with pytest.raises(InvariantViolation) as err:
        InvariantMonitor.check_storm_hygiene(summary)
    assert err.value.kind == "storm-attribution"


def test_monitor_economy_catches_starvation_and_stuck_demotion():
    ok = {
        "overlay": [
            {"epoch": 1, "windows_exhausted": 2, "fallback_engaged": 2}
        ],
        "honest_demoted_final": [],
    }
    InvariantMonitor.check_campaign_economy(ok)
    with pytest.raises(InvariantViolation) as err:
        InvariantMonitor.check_campaign_economy(
            dict(ok, honest_demoted_final=[12])
        )
    assert err.value.kind == "campaign-demotion"
    starved = dict(
        ok,
        overlay=[
            {"epoch": 1, "windows_exhausted": 2, "fallback_engaged": 0}
        ],
    )
    with pytest.raises(InvariantViolation) as err:
        InvariantMonitor.check_campaign_economy(starved)
    assert err.value.kind == "campaign-starvation"


# ---------------------------------------------------------------- families


def test_storm_runs_clean_and_reputation_cuts_post_verify_cost():
    gated = run_campaign(_cfg("storm"))
    assert gated.ok, gated.violations
    control = run_campaign(_cfg("storm", reputation=False))
    assert control.ok, control.violations
    failed = lambda o: sum(w["failed_rows"] for w in o.summary["waves"])
    # The loop's receipt: demoted forgers shed pre-verify, so the gated
    # run pays the forged verify bill once, the control every wave.
    assert failed(gated) < failed(control)
    assert gated.summary["gate"]["demotions"] >= 1
    # Honest admission survives the storm: the final wave admits at
    # least the full honest workload.
    assert (
        gated.summary["waves"][-1]["admitted"]
        >= gated.summary["honest_rows"]
    )


def test_capture_holds_proportionality_over_trajectory():
    out = run_campaign(_cfg("capture"))
    assert out.ok, out.violations
    traj = out.summary["trajectory"]
    assert len(traj) == 4
    # The grinder commits its best candidate: committed seats can never
    # fall below the passive (candidate-0) baseline it also probed.
    assert all(r["seats"] >= r["passive_seats"] for r in traj)
    assert out.summary["seats_total"] >= out.summary["passive_total"]


def test_coincidence_runs_clean_with_all_three_pressures():
    out = run_campaign(_cfg("coincidence"))
    assert out.ok, out.violations
    assert out.summary["honest_demoted_final"] == []
    assert len(out.summary["overlay"]) == 4
    # The slice really engaged: at least one epoch charged withheld
    # slots, and the storm leg really verified rows.
    assert any(r["sliced"] for r in out.summary["overlay"])
    assert any(r["verified_rows"] for r in out.summary["storm"])


def test_replay_is_digest_identical_for_every_family(tmp_path):
    for family in FAMILIES:
        out = run_campaign(_cfg(family))
        path = tmp_path / (family + ".bin")
        out.record.dump(str(path))
        loaded = CampaignRecord.load_file(str(path))
        same, fresh = replay_campaign(loaded)
        assert same, (family, loaded.digest, fresh.digest)
        assert fresh.summary == out.summary


def test_run_campaign_differs_across_seeds_not_processes():
    a = run_campaign(_cfg("capture", seed=1))
    b = run_campaign(_cfg("capture", seed=1))
    c = run_campaign(_cfg("capture", seed=2))
    assert a.digest == b.digest
    assert a.digest != c.digest


# --------------------------------------------------------------------- CLI


def test_cli_run_then_replay_roundtrip(tmp_path, capsys):
    from hyperdrive_tpu.campaign.__main__ import main

    ok_dir = str(tmp_path / "ok")
    rc = main([
        "run", "--family", "storm", "--seed", "3",
        "--validators", "64", "--committee", "16", "--attackers", "4",
        "--waves", "3", "--attack-rate", "4", "--sybils", "8",
        "--dump-ok", ok_dir,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "campaign storm" in out and "VIOLATION" not in out
    dump = next((tmp_path / "ok").glob("*.bin"))
    rc = main(["replay", str(dump)])
    assert rc == 0
    assert "digest-identical" in capsys.readouterr().out
