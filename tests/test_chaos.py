"""Chaos engine: partitions, crash-restart recovery, invariant monitor.

The deterministic side (FaultPlan interpreted by the Simulation, with
lifecycle-op record/replay) and the real-socket side (ChaosProxy in
front of TcpNode) of hyperdrive_tpu/chaos — plus the ISSUE acceptance
scenario: partition f replicas, crash one and restore it from its
checkpoint mid-run, heal, and watch every honest replica commit the
same values within bounded rounds, asserted by the InvariantMonitor,
with the dump replaying message-for-message.
"""

import socket
import threading
import time

import pytest

from hyperdrive_tpu.chaos import (
    ChaosProxy,
    CrashRestart,
    FaultPlan,
    InvariantMonitor,
    InvariantViolation,
    LinkFault,
    Partition,
)
from hyperdrive_tpu.harness.sim import ScenarioRecord, Simulation

# ----------------------------------------------------------- acceptance


def _chaos_sim(plan, n=7, target=10, seed=2024, **kw):
    kw.setdefault("timeout", 1.0)
    kw.setdefault("delivery_cost", 1e-3)
    kw.setdefault("observe", True)
    return Simulation(
        n=n, target_height=target, seed=seed, chaos=plan, **kw
    )


def test_partition_crash_restore_heal_commits_everywhere(tmp_path):
    # The ISSUE acceptance scenario: isolate f=2 replicas, crash one of
    # them mid-run, restore it from its checkpoint while still cut off,
    # heal — every honest replica commits the same value at every
    # overlapping height, within the monitor's round bound, and the
    # dumped record replays deterministically.
    plan = FaultPlan(
        partitions=(Partition(at=0.3, heal=2.5, groups=((5, 6),)),),
        crashes=(
            CrashRestart(
                replica=6, crash_at_step=420, restart_after_steps=300
            ),
        ),
        links=(
            LinkFault(
                src=0, dst=3, drop=0.05, duplicate=0.05, delay=0.1,
                delay_min=0.01, delay_max=0.1,
            ),
        ),
    )
    sim = _chaos_sim(plan)
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=500_000)

    assert result.completed
    monitor.check_final(result)  # safety + digest + journal + liveness
    # The scenario actually happened: a crash, a checkpoint restore,
    # and a heal, all observable through the monitor's lifecycle log.
    assert monitor.crashes and monitor.restores and monitor.heals
    assert [v for v, _ in monitor.crashes] == [6]
    # Post-heal commits landed within the round bound.
    assert monitor.commit_rounds_after_heal
    assert max(monitor.commit_rounds_after_heal) <= 12
    # Commit-digest equality on every overlapping height, network-wide.
    for i in range(sim.n):
        for h, v in result.commits[i].items():
            assert monitor.chain[h] == v
    # A 2f+1 quorum committed the target height itself.
    at_target = [
        i for i in range(sim.n)
        if result.commits[i].get(sim.target_height) is not None
    ]
    assert len(at_target) >= 2 * sim.f + 1

    # The chaos lifecycle rode the record: dump -> load -> replay
    # reproduces the live run's commits byte-for-byte.
    path = str(tmp_path / "acceptance.bin")
    sim.record.dump(path)
    loaded = ScenarioRecord.load(path)
    assert loaded.lifecycle == sim.record.lifecycle
    kinds = {k for k, _, _, _ in loaded.lifecycle}
    assert ScenarioRecord.OP_CRASH in kinds
    assert ScenarioRecord.OP_RESTORE in kinds
    replayed = Simulation.replay(loaded)
    assert replayed.commits == result.commits


def test_chaos_run_emits_lifecycle_events():
    plan = FaultPlan(
        partitions=(Partition(at=0.2, heal=1.8, groups=((3,),)),),
        crashes=(
            CrashRestart(
                replica=3, crash_at_step=150, restart_after_steps=200
            ),
        ),
    )
    sim = _chaos_sim(plan, n=4, target=6, seed=11)
    InvariantMonitor(sim)
    result = sim.run(max_steps=200_000)
    assert result.completed
    kinds = {ev.kind for ev in sim.obs.snapshot()}
    assert {
        "chaos.partition", "chaos.heal", "chaos.crash", "chaos.restore"
    } <= kinds


def test_same_plan_same_seed_is_bit_deterministic():
    plan = FaultPlan(
        links=(
            LinkFault(src=0, dst=2, drop=0.1, duplicate=0.1),
            LinkFault(src=3, dst=1, delay=0.2, delay_min=0.01,
                      delay_max=0.05),
        ),
        partitions=(Partition(at=0.4, heal=1.6, groups=((2,),)),),
    )
    runs = []
    for _ in range(2):
        sim = _chaos_sim(plan, n=4, target=6, seed=99, observe=False)
        res = sim.run(max_steps=200_000)
        res.assert_safety()
        runs.append((res.commits, res.steps, res.commit_digest()))
    assert runs[0] == runs[1]


def test_crash_before_any_checkpoint_restarts_from_genesis():
    # A victim crashed on the very first delivery has no checkpoint;
    # restore falls back to the default genesis state and the replica
    # still rejoins and the network completes.
    plan = FaultPlan(
        crashes=(
            CrashRestart(
                replica=2, crash_at_step=1, restart_after_steps=120
            ),
        ),
    )
    sim = _chaos_sim(plan, n=4, target=5, seed=5)
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=200_000)
    assert result.completed
    monitor.check_final(result)
    assert monitor.restores


def test_seeded_plans_are_reproducible_and_valid():
    for seed in range(0, 40):
        for n in (4, 7):
            a = FaultPlan.seeded(seed, n)
            b = FaultPlan.seeded(seed, n)
            assert a == b
            a.validate(n)  # seeded() already validates; must not raise


# ------------------------------------------------------------ plan DSL


@pytest.mark.parametrize(
    "plan",
    [
        FaultPlan(links=(LinkFault(src=0, dst=9),)),
        FaultPlan(links=(LinkFault(src=0, dst=1, drop=1.5),)),
        FaultPlan(
            links=(LinkFault(src=0, dst=1, delay_min=0.5, delay_max=0.1),)
        ),
        FaultPlan(partitions=(Partition(at=2.0, heal=1.0, groups=()),)),
        FaultPlan(partitions=(Partition(at=0.0, heal=1.0, groups=((9,),)),)),
        FaultPlan(
            partitions=(Partition(at=0.0, heal=1.0, groups=((1,), (1, 2))),)
        ),
        FaultPlan(
            crashes=(
                CrashRestart(replica=0, crash_at_step=5),
                CrashRestart(replica=0, crash_at_step=9),
            )
        ),
        FaultPlan(crashes=(CrashRestart(replica=1, crash_at_step=0),)),
        FaultPlan(
            crashes=(
                CrashRestart(
                    replica=1, crash_at_step=5, restart_after_steps=0
                ),
            )
        ),
    ],
)
def test_faultplan_validate_rejects(plan):
    with pytest.raises(ValueError):
        plan.validate(4)


def test_chaos_requires_lockstep_mode():
    with pytest.raises(ValueError, match="lock-step"):
        Simulation(
            n=4, target_height=3, seed=1, burst=True, chaos=FaultPlan()
        )


def test_partitions_require_delivery_pacing():
    plan = FaultPlan(
        partitions=(Partition(at=0.1, heal=1.0, groups=((0,),)),)
    )
    with pytest.raises(ValueError, match="delivery_cost"):
        Simulation(n=4, target_height=3, seed=1, chaos=plan)


# ------------------------------------------------------------- monitor


def test_monitor_raises_on_fork():
    sim = _chaos_sim(FaultPlan(), n=4, target=3, seed=1, observe=False)
    monitor = InvariantMonitor(sim)
    monitor._commit(0, 1, b"\xaa" * 32)
    with pytest.raises(InvariantViolation, match="fork") as ei:
        monitor._commit(1, 1, b"\xbb" * 32)
    assert ei.value.kind == "fork"
    # Agreement on the same value is never a fork.
    monitor._commit(2, 1, b"\xaa" * 32)


def test_monitor_enforces_round_bound_after_heal():
    sim = _chaos_sim(FaultPlan(), n=4, target=3, seed=1, observe=False)
    monitor = InvariantMonitor(sim, max_rounds_after_heal=0)
    monitor.note_heal(0.5)
    assert monitor._await_heal_commit == {0, 1, 2, 3}
    with pytest.raises(InvariantViolation, match="liveness"):
        monitor._commit(0, 1, b"\xcc" * 32)


def test_monitor_flags_stalled_run():
    # 2f replicas dead from the start: the network can never commit,
    # and check_final must say so instead of passing vacuously.
    sim = Simulation(
        n=4, target_height=3, seed=3, offline={2, 3}, chaos=FaultPlan()
    )
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=20_000)
    assert not result.completed
    with pytest.raises(InvariantViolation, match="liveness"):
        monitor.check_final(result)


# ------------------------------------------------------ record trailer


def test_lifecycle_trailer_roundtrips(tmp_path):
    rec = ScenarioRecord(seed=7, n=4, f=1, target_height=5)
    rec.signatories = [bytes([i]) * 32 for i in range(4)]
    rec.lifecycle = [
        (ScenarioRecord.OP_CRASH, 10, 2, 0),
        (ScenarioRecord.OP_RESTORE, 40, 2, 3),
        (ScenarioRecord.OP_RESYNC, 55, 1, 4),
    ]
    path = str(tmp_path / "trailer.bin")
    rec.dump(path)
    loaded = ScenarioRecord.load(path)
    assert loaded.lifecycle == rec.lifecycle
    assert loaded.signatories == rec.signatories


# ----------------------------------------------------------- soak CLI


def test_soak_cli_passes_and_replays(tmp_path, capsys):
    from hyperdrive_tpu.chaos.__main__ import main

    rc = main([
        "soak", "--scenarios", "2", "--seed", "7", "--n", "4",
        "--target", "5", "--replay-every", "1",
        "--out", str(tmp_path / "failures"),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "soak ok: 2 scenarios" in out
    assert not (tmp_path / "failures").exists()


def test_replay_cli_reproduces_dump(tmp_path, capsys):
    from hyperdrive_tpu.chaos.__main__ import main

    plan = FaultPlan.seeded(3, 4)
    sim = _chaos_sim(plan, n=4, target=5, seed=3, observe=False)
    result = sim.run(max_steps=200_000)
    assert result.completed
    path = str(tmp_path / "scenario.bin")
    sim.record.dump(path)
    rc = main(["replay", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "completed=True" in out


# -------------------------------------------------------- chaos proxy


def _signed_prevote(idx=0, height=1):
    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.messages import Prevote

    ring = KeyRing.deterministic(max(idx + 1, 1), namespace=b"chaosprox")
    return ring[idx].sign_message(
        Prevote(
            height=height, round=0, value=b"\x07" * 32,
            sender=ring[idx].public,
        )
    )


def _sink_node():
    from hyperdrive_tpu.transport import TcpNode

    received = []

    class _Sink:
        def propose(self, m, stop=None):
            received.append(m)

        prevote = precommit = timeout = propose

    node = TcpNode()
    node.add_replica(_Sink())
    node.start()
    return node, received


def _await(predicate, deadline_s=5.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def test_proxy_forwards_then_blackholes_then_heals():
    from hyperdrive_tpu.transport import encode_frame

    node, received = _sink_node()
    proxy = ChaosProxy("127.0.0.1", node.port).start()
    try:
        pv = _signed_prevote()
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            s.sendall(encode_frame(pv))
            assert _await(lambda: len(received) == 1)

            proxy.partition()
            s.sendall(encode_frame(pv))
            assert _await(lambda: proxy.dropped == 1)
            assert len(received) == 1  # black-holed, connection alive

            proxy.heal()
            s.sendall(encode_frame(pv))
            assert _await(lambda: len(received) == 2)
        assert proxy.forwarded == 2
    finally:
        proxy.stop()
        node.stop()


def test_proxy_drop_all_counts_every_frame():
    from hyperdrive_tpu.transport import encode_frame

    node, received = _sink_node()
    proxy = ChaosProxy("127.0.0.1", node.port, drop=1.0, seed=4).start()
    try:
        pv = _signed_prevote()
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            for _ in range(5):
                s.sendall(encode_frame(pv))
            assert _await(lambda: proxy.dropped == 5)
        assert proxy.forwarded == 0
        assert received == []
    finally:
        proxy.stop()
        node.stop()


def test_proxy_duplicate_delivers_twice():
    from hyperdrive_tpu.transport import encode_frame

    node, received = _sink_node()
    proxy = ChaosProxy(
        "127.0.0.1", node.port, duplicate=1.0, seed=4
    ).start()
    try:
        pv = _signed_prevote()
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            s.sendall(encode_frame(pv))
            assert _await(lambda: len(received) == 2)
        assert proxy.forwarded == 2
    finally:
        proxy.stop()
        node.stop()


def test_proxy_fuzz_mutates_on_cadence_and_keeps_framing():
    # ISSUE 18: fuzz_every=3 mutates exactly every 3rd forwarded frame's
    # PAYLOAD while keeping the stream parseable — the target's read
    # loop survives all mutants, delivers every clean frame (FIFO), and
    # never counts an oversize frame (the corruption is the payload's,
    # never the length prefix's).
    from hyperdrive_tpu.transport import encode_frame

    node, received = _sink_node()
    proxy = ChaosProxy(
        "127.0.0.1", node.port, seed=7, fuzz_every=3
    ).start()
    try:
        frame = encode_frame(_signed_prevote())
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            for _ in range(12):
                s.sendall(frame)
            assert _await(lambda: proxy.forwarded == 12)
            assert proxy.fuzzed == 4
            # 8 clean frames must all deliver; mutants may or may not
            # decode, and a decoded wire Timeout is silently dropped.
            assert _await(lambda: len(received) >= 8)
            assert len(received) <= 12
            # The read loop is still alive: one more clean frame
            # (13 % 3 != 0) delivers on the same connection.
            before = len(received)
            s.sendall(frame)
            assert _await(lambda: len(received) > before)
        assert node.oversize_frames == 0
        assert node.malformed_frames <= proxy.fuzzed
    finally:
        proxy.stop()
        node.stop()


def test_proxy_fuzz_rejects_negative_cadence():
    import pytest

    with pytest.raises(ValueError, match="fuzz_every"):
        ChaosProxy("127.0.0.1", 1, fuzz_every=-1)


def test_transparent_proxy_consensus_smoke():
    # Four single-replica nodes over real sockets, with every inbound
    # frame to node 3 routed through a faultless ChaosProxy: the proxy
    # is transparent to consensus, and all four commit the same chain.
    import os
    import sys

    from hyperdrive_tpu.crypto.keys import KeyRing
    from hyperdrive_tpu.transport import TcpNode

    sys.path.insert(0, os.path.dirname(__file__))
    from transport_worker import commits_digest, run_local_replicas

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    nodes = [TcpNode() for _ in range(4)]
    proxy = ChaosProxy("127.0.0.1", nodes[3].port).start()
    ports = [n.port for n in nodes[:3]] + [proxy.port]
    try:
        for a in range(4):
            for b in range(4):
                if a != b:
                    nodes[a].add_peer("127.0.0.1", ports[b])

        target = 5
        results = [None] * 4
        errors = []

        def drive(i):
            try:
                results[i] = run_local_replicas(
                    nodes[i], ring, (i,), target, deadline_s=90.0
                )
            except Exception as e:  # pragma: no cover - failure report
                errors.append((i, e))

        drivers = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in drivers:
            t.start()
        for t in drivers:
            t.join(timeout=120.0)
        assert not errors, errors
        assert all(r is not None for r in results)
        digests = [commits_digest(r) for r in results]
        assert len(set(digests)) == 1, "chains diverged through proxy"
        assert proxy.forwarded > 0
    finally:
        proxy.stop()
        for n in nodes:
            n.stop()


# ------------------------------------------- pipelined settle under chaos


def _pipelined_chaos_sim(plan, n=7, target=10, seed=2024, depth=8, **kw):
    """A chaos sim whose replicas flush through one shared async
    device-work queue (jax-free :class:`QueueFlusher` — the soak's
    pure-host engine), so settles are in flight when faults land."""
    from hyperdrive_tpu.devsched import DeviceWorkQueue, QueueFlusher
    from hyperdrive_tpu.verifier import NullVerifier

    queue = DeviceWorkQueue(max_depth=depth)
    sim = _chaos_sim(
        plan,
        n=n,
        target=target,
        seed=seed,
        devsched=queue,
        flusher_for=lambda i, validators: QueueFlusher(
            NullVerifier(), queue
        ),
        **kw,
    )
    return sim, queue


def test_pipelined_settle_survives_crash_restart_and_partition():
    # The devsched chaos scenario: partition two replicas, crash one
    # with queue-backed settles outstanding (restore cancels its dead
    # incarnation's in-flight windows), heal — the InvariantMonitor
    # proves no fork, and the agreed chain is byte-identical to the
    # same plan run with blocking flushes.
    plan = FaultPlan(
        partitions=(Partition(at=0.3, heal=2.5, groups=((5, 6),)),),
        crashes=(
            CrashRestart(
                replica=6, crash_at_step=420, restart_after_steps=300
            ),
        ),
    )
    sim, queue = _pipelined_chaos_sim(plan)
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=500_000)
    assert result.completed
    monitor.check_final(result)
    assert monitor.crashes and monitor.restores and monitor.heals
    # Pipelining actually happened: windows coalesced across replicas
    # into shared launches, and nothing was left undrained at exit.
    assert queue.coalesced > 0
    assert queue.depth == 0
    # The crash found settles in flight often enough to matter; the
    # restored replica's flusher was reset rather than replaying them.
    flushers = [r.flusher for r in sim.replicas]
    assert all(not f._inflight for f in flushers)
    assert sum(f.dispatched for f in flushers) <= sum(
        f.submitted for f in flushers
    )

    baseline = _chaos_sim(plan)
    base_result = baseline.run(max_steps=500_000)
    assert base_result.completed
    assert result.commit_digest() == base_result.commit_digest()


def test_pipelined_chaos_digest_parity_across_seeded_plans():
    # Sweep seeded fault plans (the soak's generator): every plan's
    # agreed chain must be identical with pipelining on and off, and
    # two pipelined runs must be bit-deterministic — same commit
    # digest AND same obs journal digest.
    for k in range(3):
        seed = 7 + k * 9973
        plan = FaultPlan.seeded(seed, 7)
        sim_a, _ = _pipelined_chaos_sim(plan, seed=seed)
        mon = InvariantMonitor(sim_a)
        res_a = sim_a.run(max_steps=500_000)
        assert res_a.completed, f"seed {seed}: pipelined run stalled"
        mon.check_final(res_a)

        sim_b, _ = _pipelined_chaos_sim(plan, seed=seed)
        res_b = sim_b.run(max_steps=500_000)
        assert res_a.commit_digest() == res_b.commit_digest()
        assert sim_a.obs.digest() == sim_b.obs.digest()

        seq = _chaos_sim(plan, seed=seed)
        res_seq = seq.run(max_steps=500_000)
        assert res_a.commit_digest() == res_seq.commit_digest(), (
            f"seed {seed}: pipelined chain diverged from sequential"
        )


def test_pipelined_chaos_emits_sched_events():
    plan = FaultPlan(
        partitions=(Partition(at=0.2, heal=1.8, groups=((3,),)),),
    )
    sim, _ = _pipelined_chaos_sim(plan, n=4, target=6, seed=11)
    result = sim.run(max_steps=200_000)
    assert result.completed
    kinds = {ev.kind for ev in sim.obs.snapshot()}
    assert {"sched.submit", "sched.coalesce", "sched.drain"} <= kinds


def test_certificate_commits_survive_partition_heal():
    # The PR 7 acceptance spot-check: with quorum certificates minted at
    # every commit, a partition + crash-restore + heal scenario must
    # still converge on the baseline chain (digest-identical), and every
    # surviving certificate must prove exactly the value the chain
    # committed at its height.
    import hashlib

    plan = FaultPlan(
        partitions=(Partition(at=0.3, heal=2.0, groups=((5, 6),)),),
        crashes=(
            CrashRestart(
                replica=6, crash_at_step=420, restart_after_steps=300
            ),
        ),
    )
    base = _chaos_sim(plan)
    base_res = base.run(max_steps=500_000)
    assert base_res.completed

    sim = _chaos_sim(plan, certificates=True)
    monitor = InvariantMonitor(sim)
    result = sim.run(max_steps=500_000)
    assert result.completed
    monitor.check_final(result)
    assert result.commit_digest() == base_res.commit_digest()

    minted = 0
    for i, certifier in enumerate(sim.certifiers):
        for h, cert in certifier.certs.items():
            v = result.commits[i].get(h)
            if v is not None:
                assert cert.value_digest == hashlib.sha256(v).digest()
            assert cert.signer_count() >= 2 * sim.f + 1
            assert certifier.verify(cert)
            minted += 1
    assert minted > 0
    # Digest equality across replicas at every shared height: two
    # replicas' certificates for the same height prove the same value.
    for h in {h for c in sim.certifiers for h in c.certs}:
        digests = {
            c.certs[h].value_digest
            for c in sim.certifiers
            if h in c.certs
        }
        assert len(digests) == 1, f"certificate fork at height {h}"
