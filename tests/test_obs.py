"""Flight recorder, anatomy report, Perfetto exporter, and obs CLI.

The sim-integration half (observed runs, digest stability) lives in
tests/analysis/test_digest_stability.py; this module specs the obs
package itself on synthetic journals plus one real observed run for the
acceptance-shaped trace checks.
"""

import json

import pytest

from hyperdrive_tpu.obs import __main__ as obs_cli
from hyperdrive_tpu.obs.perfetto import PID, export, to_trace_events
from hyperdrive_tpu.obs.recorder import (
    EVENT_KINDS,
    NULL_BOUND,
    Event,
    Recorder,
    load_journal,
)
from hyperdrive_tpu.obs.report import anatomy, phase_summary, render_table


# ------------------------------------------------------------------ recorder


def test_recorder_orders_events_and_binds_scopes():
    rec = Recorder(capacity=16)
    r0 = rec.scoped(0)
    r1 = rec.scoped(1)
    r0.emit("round.start", 1, 0)
    r1.emit("round.start", 1, 0)
    r0.emit("commit", 1, 0, "aa")
    evs = rec.snapshot()
    assert [e.kind for e in evs] == ["round.start", "round.start", "commit"]
    assert [e.replica for e in evs] == [0, 1, 0]
    assert evs[2].detail == "aa"
    # The fallback clock is strictly increasing.
    assert evs[0].ts < evs[1].ts < evs[2].ts
    assert len(rec) == 3 and rec.dropped == 0


def test_recorder_ring_keeps_newest_and_counts_drops():
    rec = Recorder(capacity=4)
    for i in range(11):
        rec.emit("commit", 0, i, 0)
    assert len(rec) == 4
    assert rec.total == 11
    assert rec.dropped == 7
    assert [e.height for e in rec.snapshot()] == [7, 8, 9, 10]


def test_recorder_rejects_nonpositive_capacity():
    with pytest.raises(ValueError):
        Recorder(capacity=0)


def test_recorder_injected_clock_stamps_events():
    now = [2.5]
    rec = Recorder(capacity=8, time_fn=lambda: now[0])
    rec.emit("commit", 0, 1, 0)
    now[0] = 3.75
    rec.emit("commit", 0, 2, 0)
    assert [e.ts for e in rec.snapshot()] == [2.5, 3.75]


def test_threadsafe_recorder_inserts_under_lock():
    rec = Recorder(capacity=8, threadsafe=True)
    rec.scoped(3).emit("wire.frame.shed", -1, -1)
    assert rec.snapshot()[0].replica == 3


def test_journal_save_load_round_trip(tmp_path):
    rec = Recorder(capacity=8)
    rec.emit("round.start", 0, 1, 0)
    rec.emit("commit", 0, 1, 0, "beef")
    path = tmp_path / "j.json"
    rec.save(path)
    journal = load_journal(path)
    assert journal["version"] == 1
    assert journal["total"] == 2 and journal["dropped"] == 0
    assert [e.kind for e in journal["events"]] == ["round.start", "commit"]
    assert isinstance(journal["events"][0], Event)
    # The digest is a function of the events alone: recomputing over the
    # reloaded journal must agree with the live recorder.
    reloaded = json.dumps(
        [list(e) for e in journal["events"]], separators=(",", ":")
    )
    live = json.dumps(
        [list(e) for e in rec.snapshot()], separators=(",", ":")
    )
    assert reloaded == live


def test_load_journal_rejects_unknown_version(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"version": 99, "events": []}')
    with pytest.raises(ValueError, match="version"):
        load_journal(p)


def test_emitted_kinds_stay_inside_the_documented_taxonomy():
    # Every kind the wired call sites emit must be in the closed set the
    # docs/report/exporter key on. Greps the package so a new emit site
    # cannot silently extend the taxonomy.
    import os
    import re

    import hyperdrive_tpu

    root = os.path.dirname(hyperdrive_tpu.__file__)
    emitted = set()
    pat = re.compile(r'\.emit\(\s*"([a-z0-9_.]+)"')
    for dirpath, _, names in os.walk(root):
        for n in names:
            if n.endswith(".py"):
                with open(os.path.join(dirpath, n)) as fh:
                    emitted.update(pat.findall(fh.read()))
    assert emitted, "sanity: the grep found the wired emit sites"
    assert emitted <= EVENT_KINDS, emitted - EVENT_KINDS


# ------------------------------------------------------------------- report


def _ev(ts, replica, height, round_, kind, detail=None):
    return Event((ts, replica, height, round_, kind, detail))


def test_anatomy_decomposes_multi_round_commit_with_flags():
    events = [
        _ev(0.0, 0, 1, 0, "round.start"),
        _ev(0.1, 0, 1, 0, "step.prevoting"),
        _ev(0.2, 0, 1, 0, "timeout.precommit.fired"),
        _ev(0.3, 0, 1, 1, "round.start"),
        _ev(0.4, 0, 1, 1, "step.prevoting"),
        _ev(0.6, 0, 1, 1, "step.precommitting"),
        _ev(0.9, 0, 1, 1, "commit", "aa"),
        # A second replica commits height 1 in one clean round.
        _ev(0.0, 1, 1, 0, "round.start"),
        _ev(0.1, 1, 1, 0, "step.prevoting"),
        _ev(0.2, 1, 1, 0, "step.precommitting"),
        _ev(0.3, 1, 1, 0, "commit", "aa"),
        # An uncommitted height must not produce a row.
        _ev(1.0, 0, 2, 0, "round.start"),
    ]
    rows = anatomy(events)
    assert [(r["replica"], r["height"]) for r in rows] == [(0, 1), (1, 1)]
    slow = rows[0]
    assert slow["rounds"] == 2
    assert slow["propose_s"] == pytest.approx(0.1)
    assert slow["prevote_s"] == pytest.approx(0.2)
    assert slow["precommit_s"] == pytest.approx(0.3)
    assert slow["stall_s"] == pytest.approx(0.3)
    assert slow["total_s"] == pytest.approx(0.9)
    assert "extra-rounds" in slow["flags"]
    assert "timeout-driven" in slow["flags"]
    clean = rows[1]
    assert clean["rounds"] == 1 and clean["stall_s"] == 0.0
    assert clean["flags"] == []


def test_anatomy_flags_slow_and_equivocation_outliers():
    events = []
    for h in range(1, 6):
        t0 = float(h)
        events += [
            _ev(t0, 0, h, 0, "round.start"),
            _ev(t0 + 0.01, 0, h, 0, "step.prevoting"),
            _ev(t0 + 0.02, 0, h, 0, "step.precommitting"),
            # Height 5 takes 10x the median commit time.
            _ev(t0 + (1.0 if h == 5 else 0.1), 0, h, 0, "commit"),
        ]
    events.append(_ev(3.005, 0, 3, 0, "equivocation", "double_prevote"))
    by_height = {r["height"]: r for r in anatomy(events)}
    assert "slow" in by_height[5]["flags"]
    assert "equivocation" in by_height[3]["flags"]
    assert by_height[2]["flags"] == []


def test_phase_summary_empty_and_populated():
    assert phase_summary([]) == {"commits": 0}
    events = [
        _ev(0.0, 0, 1, 0, "round.start"),
        _ev(0.1, 0, 1, 0, "step.prevoting"),
        _ev(0.3, 0, 1, 0, "step.precommitting"),
        _ev(0.6, 0, 1, 0, "commit"),
    ]
    s = phase_summary(events)
    assert s["commits"] == 1
    assert s["mean_rounds"] == 1.0
    assert s["mean_propose_s"] == pytest.approx(0.1)
    assert s["mean_prevote_s"] == pytest.approx(0.2)
    assert s["mean_precommit_s"] == pytest.approx(0.3)
    assert s["mean_total_s"] == pytest.approx(0.6)
    assert s["timeout_driven"] == 0


def test_render_table_aligns_and_marks_missing():
    rows = anatomy([
        _ev(0.0, 0, 1, 0, "round.start"),
        _ev(0.5, 0, 1, 0, "commit"),
    ])
    text = render_table(rows)
    lines = text.splitlines()
    assert lines[0].split() == [
        "ht", "rep", "rnds", "propose", "prevote", "precommit",
        "stall", "total", "t/o", "flags",
    ]
    assert set(lines[1]) <= {"-", " "}
    # Phases without step markers render as '-', the total still appears.
    assert "-" in lines[2] and "0.5000" in lines[2]


# ----------------------------------------------------------------- perfetto


def _tracks(trace):
    by_tid = {}
    for ev in trace:
        if ev["ph"] in ("B", "E", "i"):
            by_tid.setdefault(ev["tid"], []).append(ev)
    return by_tid


def test_trace_events_are_schema_valid_and_monotonic_per_track():
    events = [
        _ev(0.0, 0, 1, 0, "round.start"),
        _ev(0.1, 0, 1, 0, "step.prevoting"),
        _ev(0.2, 0, 1, 0, "step.precommitting"),
        _ev(0.2, 0, 1, 0, "timeout.precommit.fired"),
        _ev(0.3, 0, 1, 0, "commit", "aa"),
        _ev(0.05, -1, -1, -1, "fetch.sync", "tally"),
    ]
    trace = to_trace_events(events)
    for ev in trace:
        assert ev["ph"] in ("B", "E", "i", "M")
        assert ev["pid"] == PID
        if ev["ph"] != "M":
            assert ev["ts"] >= 0.0
            assert "tid" in ev
        if ev["ph"] in ("B", "i"):
            assert ev["name"]
    for tid, evs in _tracks(trace).items():
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts), f"tid {tid} timestamps regress"
    # Spans balance per track: every B has its E.
    for tid, evs in _tracks(trace).items():
        depth = 0
        for e in evs:
            if e["ph"] == "B":
                depth += 1
            elif e["ph"] == "E":
                depth -= 1
                assert depth >= 0
        assert depth == 0, f"tid {tid} leaves open spans"
    # Track metadata labels replicas and the sim-global lane.
    names = {
        ev["tid"]: ev["args"]["name"]
        for ev in trace
        if ev["ph"] == "M" and ev["name"] == "thread_name"
    }
    assert names[0] == "replica 0"
    assert names[-1] == "sim"


def test_trace_instants_carry_height_round_and_detail():
    trace = to_trace_events([
        _ev(0.1, 2, 4, 1, "equivocation", "double_prevote"),
    ])
    inst = [e for e in trace if e["ph"] == "i"]
    assert len(inst) == 1
    assert inst[0]["s"] == "t"
    assert inst[0]["args"] == {
        "height": 4, "round": 1, "detail": "double_prevote",
    }


def test_export_writes_loadable_doc(tmp_path):
    path = tmp_path / "trace.json"
    doc = export([_ev(0.0, 0, 1, 0, "commit")], path)
    on_disk = json.loads(path.read_text())
    assert on_disk == doc
    assert on_disk["displayTimeUnit"] == "ms"


# ---------------------------------------------- observed sim (acceptance)


@pytest.fixture(scope="module")
def observed_sim():
    from hyperdrive_tpu.harness import Simulation

    sim = Simulation(
        n=4, target_height=3, seed=91, timeout=20.0,
        delivery_cost=0.001, observe=True,
    )
    res = sim.run()
    assert res.completed
    return sim


def test_observed_run_trace_has_round_phase_spans_and_commits(observed_sim):
    events = observed_sim.obs.snapshot()
    trace = to_trace_events(events)
    rounds = [e for e in trace if e["ph"] == "B" and e["cat"] == "round"]
    phases = {e["name"] for e in trace if e["ph"] == "B" and e["cat"] == "phase"}
    commits = [e for e in trace if e["ph"] == "i" and e["name"] == "commit"]
    assert phases == {"propose", "prevote", "precommit"}
    # Every replica opens round spans for multiple heights and commits
    # at least once — the 4-replica multi-height acceptance shape.
    for tid in range(4):
        assert sum(1 for e in rounds if e["tid"] == tid) >= 3
        assert any(e["tid"] == tid for e in commits)


def test_offline_proposer_run_records_timeout_instants():
    from hyperdrive_tpu.harness import Simulation

    sim = Simulation(
        n=4, target_height=2, seed=7, timeout=1.0,
        offline={1}, observe=True,
    )
    sim.run(max_steps=20000)
    events = sim.obs.snapshot()
    fired = {e.kind for e in events if e.kind.startswith("timeout.")}
    assert any(k.endswith(".fired") for k in fired), fired
    trace = to_trace_events(events)
    assert any(
        e["ph"] == "i" and e["name"].startswith("timeout") for e in trace
    )


def test_disabled_recording_leaves_replica_on_null_bound():
    from hyperdrive_tpu.harness import Simulation

    sim = Simulation(n=4, target_height=1, seed=91)
    assert sim._obs_sim is NULL_BOUND
    assert sim.replicas[0].obs is NULL_BOUND
    assert sim.replicas[0].proc.obs is NULL_BOUND
    sim.run()
    assert len(sim.obs) == 0


# ---------------------------------------------------------------------- CLI


def test_cli_record_report_export_round_trip(tmp_path, capsys):
    journal = str(tmp_path / "journal.json")
    trace = str(tmp_path / "trace.json")
    assert obs_cli.main([
        "record", "-o", journal, "--replicas", "4", "--heights", "2",
    ]) == 0
    rec_out = json.loads(capsys.readouterr().out)
    assert rec_out["completed"] is True and rec_out["events"] > 0

    assert obs_cli.main(["report", journal]) == 0
    report_out = capsys.readouterr().out
    assert "commits" in report_out and "mean rounds" in report_out

    assert obs_cli.main(["report", journal, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows["summary"]["commits"] >= 8  # 4 replicas x 2 heights

    assert obs_cli.main(["export", journal, "-o", trace]) == 0
    exp_out = json.loads(capsys.readouterr().out)
    assert exp_out["events"] > 0
    assert json.loads(open(trace).read())["traceEvents"]


def test_cli_report_empty_journal_exits_nonzero(tmp_path, capsys):
    rec = Recorder(capacity=4)
    rec.emit("round.start", 0, 1, 0)  # no commit: no anatomy rows
    path = str(tmp_path / "empty.json")
    rec.save(path)
    assert obs_cli.main(["report", path]) == 1
    assert "no committed heights" in capsys.readouterr().out
