"""The dense struct-array message layer vs the object layer.

MessageBlock must be a lossless columnar view: round-trip equality,
digest equality with the per-object path, verifier-feed equivalence, and
tally tensors that agree with hand-counted quorums.
"""

import numpy as np
import pytest

from hyperdrive_tpu.batch import MessageBlock
from hyperdrive_tpu.messages import Precommit, Prevote, Propose, Timeout
from hyperdrive_tpu.ops.tally import quorum_flags, tally_counts
from hyperdrive_tpu.testutil import (
    random_precommit,
    random_prevote,
    random_propose,
)
from hyperdrive_tpu.types import INVALID_ROUND, MessageType


def sample_messages(rng, n=40):
    msgs = []
    for i in range(n):
        gen = (random_propose, random_prevote, random_precommit)[i % 3]
        m = gen(rng)
        if i % 4 == 0:
            m = m.with_signature(rng.randbytes(64))
        if isinstance(m, Propose) and i % 6 == 0:
            m = Propose(
                height=m.height,
                round=m.round,
                valid_round=m.valid_round,
                value=m.value,
                sender=m.sender,
                payload=rng.randbytes(50),
            )
        msgs.append(m)
    return msgs


def test_round_trip_exact(rng):
    msgs = sample_messages(rng)
    block = MessageBlock.from_messages(msgs)
    back = block.to_messages()
    assert back == msgs
    for a, b in zip(msgs, back):
        assert a.signature == b.signature or (
            not a.signature and not b.signature
        )


def test_digests_match_object_path(rng):
    msgs = sample_messages(rng)
    block = MessageBlock.from_messages(msgs)
    assert block.digests() == [m.digest() for m in msgs]


def test_verify_items_match_object_path(rng):
    msgs = sample_messages(rng)
    block = MessageBlock.from_messages(msgs)
    for (pub, digest, sig), m in zip(block.verify_items(), msgs):
        assert pub == m.sender
        assert digest == m.digest()
        if m.signature and len(m.signature) == 64:
            assert sig == m.signature
        else:
            # Deterministic rejection: empty sig fails the packer's length
            # check; the zero row padding must never reach the verifier.
            assert sig == b""


def test_pack_arrays_shapes(rng):
    msgs = sample_messages(rng, n=12)
    pubs, digests, sigs, has_sig = MessageBlock.from_messages(msgs).pack_arrays()
    assert pubs.shape == (12, 32) and pubs.dtype == np.uint8
    assert digests.shape == (12, 32)
    assert sigs.shape == (12, 64)
    assert digests[3].tobytes() == msgs[3].digest()
    assert list(has_sig) == [
        bool(m.signature and len(m.signature) == 64) for m in msgs
    ]


def test_timeouts_are_rejected():
    with pytest.raises(TypeError):
        MessageBlock.from_messages(
            [Timeout(message_type=MessageType.PREVOTE, height=1, round=0)]
        )


def test_tally_inputs_count_quorums(rng):
    sigs = [bytes([i]) * 32 for i in range(7)]  # n=7, f=2, quorum=5
    target = b"\x2a" * 32
    other = b"\x2b" * 32
    msgs = []
    # Round 0: 5 votes for target, 1 for other, duplicate from sender 0.
    for i in range(5):
        msgs.append(Prevote(height=3, round=0, value=target, sender=sigs[i]))
    msgs.append(Prevote(height=3, round=0, value=other, sender=sigs[5]))
    msgs.append(Prevote(height=3, round=0, value=other, sender=sigs[0]))  # dup
    # Round 2: only 3 votes. Other heights/types must be ignored.
    for i in range(3):
        msgs.append(Prevote(height=3, round=2, value=target, sender=sigs[i]))
    msgs.append(Prevote(height=9, round=0, value=target, sender=sigs[6]))
    msgs.append(Precommit(height=3, round=0, value=target, sender=sigs[6]))
    msgs.append(Prevote(height=3, round=0, value=target, sender=b"\xee" * 32))

    block = MessageBlock.from_messages(msgs)
    rounds, vote_vals, present = block.tally_inputs(
        sigs, MessageType.PREVOTE, height=3
    )
    assert rounds == [0, 2]
    counts = tally_counts(
        vote_vals,
        present,
        np.broadcast_to(
            np.frombuffer(target, dtype="<i4").astype(np.int32), (2, 8)
        ),
    )
    assert list(np.asarray(counts["matching"])) == [5, 3]
    assert list(np.asarray(counts["total"])) == [6, 3]
    flags = quorum_flags(counts, np.int32(2))
    assert list(np.asarray(flags["quorum_matching"])) == [True, False]
