"""Loopback-TCP Broadcaster: consensus over real sockets.

The Broadcaster seam bound to a wire (hyperdrive_tpu/transport.py):
full-mesh TCP, length-framed signed envelopes, threaded replicas, real
LinearTimer timeouts. The reference never ships a network binding (its
tests use an in-memory queue, replica/replica_test.go:174-208); this is
the seam-to-proof upgrade — including a 2-OS-process run.
"""

import os
import socket
import subprocess
import sys

import pytest

from hyperdrive_tpu.codec import Reader, Writer
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote, marshal_message
from hyperdrive_tpu.transport import TcpNode, encode_frame

sys.path.insert(0, os.path.dirname(__file__))
from transport_worker import (  # noqa: E402
    commits_digest,
    deterministic_value,
    run_local_replicas,
)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_frame_roundtrip_carries_signature():
    ring = KeyRing.deterministic(1, namespace=b"frame")
    pv = ring[0].sign_message(
        Prevote(height=3, round=1, value=b"\x07" * 32, sender=ring[0].public)
    )
    frame = encode_frame(pv)
    from hyperdrive_tpu.messages import unmarshal_message

    got = unmarshal_message(Reader(frame[4:]))
    assert got == pv and got.signature == pv.signature


def test_four_nodes_commit_ten_heights_over_sockets():
    # Four single-replica nodes in one process, real sockets between them:
    # every replica commits 10 heights, chains byte-identical.
    import threading

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    nodes = [TcpNode() for _ in range(4)]
    for a in range(4):
        for b in range(4):
            if a != b:
                nodes[a].add_peer("127.0.0.1", nodes[b].port)

    target = 10
    results = [None] * 4
    errors = []

    def drive(i):
        try:
            results[i] = run_local_replicas(
                nodes[i], ring, (i,), target, deadline_s=90.0
            )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    drivers = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(4)
    ]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=120.0)
    assert not errors, errors
    assert all(r is not None for r in results)
    digests = [commits_digest(r) for r in results]
    assert len(set(digests)) == 1, "commit chains diverged across nodes"
    chain = results[0][0]
    assert set(chain.keys()) == set(range(1, target + 1))
    # Values are the deterministic proposer's (h, round) digests.
    assert chain[1] in {deterministic_value(1, r) for r in range(3)}


def test_three_of_four_commit_with_one_dead_peer():
    # f = 1 crash tolerance over the wire: the fourth validator never
    # comes up (its port refuses connections); the three live nodes'
    # sender threads retry in the background without ever blocking a
    # broadcast, and the 2f+1 quorum commits.
    import threading

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    (dead_port,) = _free_ports(1)
    nodes = [TcpNode() for _ in range(3)]
    ports = [n.port for n in nodes] + [dead_port]
    for a in range(3):
        for b in range(4):
            if ports[a] != ports[b]:
                nodes[a].add_peer("127.0.0.1", ports[b])

    target = 5
    results = [None] * 3
    errors = []

    def drive(i):
        try:
            results[i] = run_local_replicas(
                nodes[i], ring, (i,), target, deadline_s=90.0
            )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    drivers = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=120.0)
    assert not errors, errors
    assert all(r is not None for r in results)
    digests = [commits_digest(r) for r in results]
    assert len(set(digests)) == 1


def test_two_process_tcp_consensus():
    # The Broadcaster seam across a REAL OS process boundary: two worker
    # processes, two replicas each, loopback TCP full mesh, signed
    # messages, real LinearTimer timeouts — 10 heights committed, commit
    # digests identical across processes.
    port_a, port_b = _free_ports(2)
    worker = os.path.join(os.path.dirname(__file__), "transport_worker.py")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    target = 10
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port_a), str(port_b), str(rank),
             str(target)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"
        assert f"TRANSPORT_OK rank={rank} heights={target}" in out, out
        outs.append(out)
    digests = [
        line.split("digest=")[1].strip()
        for out in outs
        for line in out.splitlines()
        if "TRANSPORT_OK" in line
    ]
    assert len(digests) == 2 and digests[0] == digests[1], (
        "commit chains diverged across processes"
    )


def test_malformed_frames_do_not_poison_the_node():
    # Garbage bytes and oversized length prefixes from a rogue peer must
    # neither crash the node nor corrupt subsequent valid frames.
    import struct
    import time as _time

    node = TcpNode()
    received = []

    class _Sink:
        def propose(self, m, stop=None):
            received.append(m)

        prevote = precommit = timeout = propose

    node.add_replica(_Sink())
    node.start()
    ring = KeyRing.deterministic(1, namespace=b"rogue")

    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(struct.pack("<I", 12) + b"\xff" * 12)  # malformed envelope
    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(struct.pack("<I", 1 << 30))  # absurd length: conn dropped
    pv = ring[0].sign_message(
        Prevote(height=1, round=0, value=b"\x01" * 32, sender=ring[0].public)
    )
    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(encode_frame(pv))
        _time.sleep(0.2)
    node.stop()
    assert pv in received


def test_writer_frame_is_parseable_by_reader():
    # encode_frame's payload is exactly one marshal_message envelope.
    ring = KeyRing.deterministic(1, namespace=b"frame2")
    pv = Prevote(height=2, round=0, value=b"\x05" * 32, sender=ring[0].public)
    w = Writer()
    marshal_message(pv, w)
    assert encode_frame(pv)[4:] == w.data()
