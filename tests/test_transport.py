"""Loopback-TCP Broadcaster: consensus over real sockets.

The Broadcaster seam bound to a wire (hyperdrive_tpu/transport.py):
full-mesh TCP, length-framed signed envelopes, threaded replicas, real
LinearTimer timeouts. The reference never ships a network binding (its
tests use an in-memory queue, replica/replica_test.go:174-208); this is
the seam-to-proof upgrade — including a 2-OS-process run.
"""

import os
import socket
import subprocess
import sys

import pytest

from hyperdrive_tpu.codec import Reader, Writer
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote, marshal_message
from hyperdrive_tpu.transport import TcpNode, encode_frame

sys.path.insert(0, os.path.dirname(__file__))
from transport_worker import (  # noqa: E402
    commits_digest,
    deterministic_value,
    run_local_replicas,
)


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_frame_roundtrip_carries_signature():
    ring = KeyRing.deterministic(1, namespace=b"frame")
    pv = ring[0].sign_message(
        Prevote(height=3, round=1, value=b"\x07" * 32, sender=ring[0].public)
    )
    frame = encode_frame(pv)
    from hyperdrive_tpu.messages import unmarshal_message

    got = unmarshal_message(Reader(frame[4:]))
    assert got == pv and got.signature == pv.signature


def test_four_nodes_commit_ten_heights_over_sockets():
    # Four single-replica nodes in one process, real sockets between them:
    # every replica commits 10 heights, chains byte-identical.
    import threading

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    nodes = [TcpNode() for _ in range(4)]
    for a in range(4):
        for b in range(4):
            if a != b:
                nodes[a].add_peer("127.0.0.1", nodes[b].port)

    target = 10
    results = [None] * 4
    errors = []

    def drive(i):
        try:
            results[i] = run_local_replicas(
                nodes[i], ring, (i,), target, deadline_s=90.0
            )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    drivers = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(4)
    ]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=120.0)
    assert not errors, errors
    assert all(r is not None for r in results)
    digests = [commits_digest(r) for r in results]
    assert len(set(digests)) == 1, "commit chains diverged across nodes"
    chain = results[0][0]
    assert set(chain.keys()) == set(range(1, target + 1))
    # Values are the deterministic proposer's (h, round) digests.
    assert chain[1] in {deterministic_value(1, r) for r in range(3)}


def test_three_of_four_commit_with_one_dead_peer():
    # f = 1 crash tolerance over the wire: the fourth validator never
    # comes up (its port refuses connections); the three live nodes'
    # sender threads retry in the background without ever blocking a
    # broadcast, and the 2f+1 quorum commits.
    import threading

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    (dead_port,) = _free_ports(1)
    nodes = [TcpNode() for _ in range(3)]
    ports = [n.port for n in nodes] + [dead_port]
    for a in range(3):
        for b in range(4):
            if ports[a] != ports[b]:
                nodes[a].add_peer("127.0.0.1", ports[b])

    target = 5
    results = [None] * 3
    errors = []

    def drive(i):
        try:
            results[i] = run_local_replicas(
                nodes[i], ring, (i,), target, deadline_s=90.0
            )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    drivers = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=120.0)
    assert not errors, errors
    assert all(r is not None for r in results)
    digests = [commits_digest(r) for r in results]
    assert len(set(digests)) == 1


def test_two_process_tcp_consensus():
    # The Broadcaster seam across a REAL OS process boundary: two worker
    # processes, two replicas each, loopback TCP full mesh, signed
    # messages, real LinearTimer timeouts — 10 heights committed, commit
    # digests identical across processes.
    port_a, port_b = _free_ports(2)
    worker = os.path.join(os.path.dirname(__file__), "transport_worker.py")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    target = 10
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port_a), str(port_b), str(rank),
             str(target)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"
        assert f"TRANSPORT_OK rank={rank} heights={target}" in out, out
        outs.append(out)
    digests = [
        line.split("digest=")[1].strip()
        for out in outs
        for line in out.splitlines()
        if "TRANSPORT_OK" in line
    ]
    assert len(digests) == 2 and digests[0] == digests[1], (
        "commit chains diverged across processes"
    )


@pytest.mark.slow  # subprocess workers recompile the wire kernels
# fresh each run; the three_of_four test keeps fast-suite transport
# coverage and the chaos soak exercises the full mesh
def test_two_process_tpu_verified_device_tally_consensus():
    # The deployment capstone: every layer of the framework in ONE
    # multi-process run. Two OS processes x two replicas, loopback-TCP
    # full mesh (Broadcaster seam over real sockets), real LinearTimer
    # timeouts, every delivered envelope verified through TpuWireVerifier
    # with a resident ValidatorTable (the grouped 69 B/lane challenge
    # format: device SHA-512 + mod-L + decompression + ladder), quorum
    # counts from per-replica n=1 device vote grids with every
    # device-sourced count cross-checked against the host counters
    # (CheckedTallyView raises on any mismatch -> worker exits nonzero).
    # 10 heights committed; commit digests identical ACROSS processes.
    # This is the reference's full-network integration
    # (replica/replica_test.go:372-430) composed with the TPU data path
    # the reference doesn't have.
    port_a, port_b = _free_ports(2)
    worker = os.path.join(os.path.dirname(__file__), "transport_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    target = 10
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port_a), str(port_b), str(rank),
             str(target), "tpu"],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"
        assert f"TRANSPORT_OK rank={rank} heights={target}" in out, out
        outs.append(out)
    fields = []
    for out in outs:
        (line,) = [ln for ln in out.splitlines() if "TRANSPORT_OK" in ln]
        fields.append(dict(
            kv.split("=", 1) for kv in line.split()[1:]
        ))
    assert fields[0]["digest"] == fields[1]["digest"], (
        "commit chains diverged across processes"
    )
    for f in fields:
        assert f["mode"] == "tpu"
        # Device tally counts were actually consulted, and envelopes
        # actually rode the grouped challenge wire format.
        assert int(f["consulted"]) > 0, fields
        assert int(f["grouped"]) > 0, fields


def test_malformed_frames_do_not_poison_the_node():
    # Garbage bytes and oversized length prefixes from a rogue peer must
    # neither crash the node nor corrupt subsequent valid frames.
    import struct
    import time as _time

    node = TcpNode()
    received = []

    class _Sink:
        def propose(self, m, stop=None):
            received.append(m)

        prevote = precommit = timeout = propose

    node.add_replica(_Sink())
    node.start()
    ring = KeyRing.deterministic(1, namespace=b"rogue")

    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(struct.pack("<I", 12) + b"\xff" * 12)  # malformed envelope
    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(struct.pack("<I", 1 << 30))  # absurd length: conn dropped
    pv = ring[0].sign_message(
        Prevote(height=1, round=0, value=b"\x01" * 32, sender=ring[0].public)
    )
    with socket.create_connection(("127.0.0.1", node.port)) as s:
        s.sendall(encode_frame(pv))
        _time.sleep(0.2)
    node.stop()
    assert pv in received


def test_flight_record_offline_replay(tmp_path):
    # Record a live socket run (4 single-replica nodes, real TCP, signed
    # envelopes, real LinearTimer), then reproduce every replica OFFLINE
    # from its flight log: fresh in-process replica, no sockets, no
    # timers (recorded Timeout events stand in for the wall clock), same
    # deterministic DI — commit chains byte-identical to the live run.
    # This is the reference's failure.dump record/replay workflow
    # (replica/replica_test.go:850-928) extended to the deployment path.
    import threading

    from hyperdrive_tpu.replica import Replica, ReplicaOptions
    from hyperdrive_tpu.testutil import (
        CommitterCallback,
        MockProposer,
        MockValidator,
    )
    from hyperdrive_tpu.transport import FlightRecorder, replay_flight
    from hyperdrive_tpu.verifier import HostVerifier

    ring = KeyRing.deterministic(4, namespace=b"tcp-demo")
    nodes = [TcpNode() for _ in range(4)]
    for a in range(4):
        for b in range(4):
            if a != b:
                nodes[a].add_peer("127.0.0.1", nodes[b].port)
    target = 5
    results = [None] * 4
    recs = [dict() for _ in range(4)]
    errors = []

    def drive(i):
        try:
            results[i] = run_local_replicas(
                nodes[i], ring, (i,), target, deadline_s=90.0,
                recorders=recs[i],
            )
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    drivers = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(4)
    ]
    for t in drivers:
        t.start()
    for t in drivers:
        t.join(timeout=120.0)
    assert not errors, errors
    assert all(r is not None for r in results)

    def offline_replica(i, commits):
        return Replica(
            ReplicaOptions(),
            whoami=ring[i].public,
            signatories=list(ring.signatories),
            timer=None,
            proposer=MockProposer(fn=deterministic_value),
            validator=MockValidator(ok=True),
            committer=CommitterCallback(
                on_commit=lambda h, v: (commits.__setitem__(h, v),
                                        (0, None))[1]
            ),
            catcher=None,
            broadcaster=None,
            verifier=HostVerifier(),
        )

    for i in range(4):
        path = tmp_path / f"flight_{i}.log"
        recs[i][i].dump(path)
        # The log round-trips (signatures included) and replays to the
        # exact live chain.
        loaded = FlightRecorder.load(path)
        assert len(loaded) == len(recs[i][i].frames)
        commits: dict = {}
        replay_flight(path, offline_replica(i, commits))
        assert commits == results[i][i], f"replica {i} replay diverged"

    # The stalled-run shape: a truncated log (the run died mid-flight)
    # still replays cleanly to a prefix of the chain.
    short = tmp_path / "flight_truncated.log"
    frames = recs[0][0].frames
    with open(short, "wb") as f:
        f.write(b"".join(frames[: len(frames) // 2]))
    commits_prefix: dict = {}
    replay_flight(short, offline_replica(0, commits_prefix))
    full = results[0][0]
    assert all(commits_prefix[h] == full[h] for h in commits_prefix)
    assert len(commits_prefix) <= len(full)

    # Mid-frame truncation — the actual killed-while-writing shape: the
    # partial trailing frame is discarded, the intact prefix replays.
    blob = b"".join(frames)
    ragged = tmp_path / "flight_ragged.log"
    with open(ragged, "wb") as f:
        f.write(blob[: len(blob) - 7])
    assert len(FlightRecorder.load(ragged)) == len(frames) - 1
    commits_ragged: dict = {}
    replay_flight(ragged, offline_replica(0, commits_ragged))
    assert all(commits_ragged[h] == full[h] for h in commits_ragged)


def test_writer_frame_is_parseable_by_reader():
    # encode_frame's payload is exactly one marshal_message envelope.
    ring = KeyRing.deterministic(1, namespace=b"frame2")
    pv = Prevote(height=2, round=0, value=b"\x05" * 32, sender=ring[0].public)
    w = Writer()
    marshal_message(pv, w)
    assert encode_frame(pv)[4:] == w.data()


def test_peer_backlog_overflow_counts_drops(caplog):
    # ISSUE 5 satellite: _PEER_QUEUE overflow sheds the oldest frame and
    # must be observable — per-peer counter, obs event, and a WARNING on
    # the FIRST drop only. The node is never start()ed, so no sender
    # thread drains the queue and the overflow is deterministic.
    import logging

    from hyperdrive_tpu.obs.recorder import Recorder
    from hyperdrive_tpu.transport import _PEER_QUEUE

    rec = Recorder(threadsafe=True)
    node = TcpNode(obs=rec.scoped(-1))
    (dead_port,) = _free_ports(1)
    try:
        node.add_peer("127.0.0.1", dead_port)
        pv = Prevote(
            height=1, round=0, value=b"\x05" * 32, sender=b"\x01" * 32
        )
        with caplog.at_level(
            logging.WARNING, logger="hyperdrive_tpu.transport"
        ):
            for _ in range(_PEER_QUEUE + 3):
                node.broadcast(pv)
        key = ("127.0.0.1", dead_port)
        assert node.dropped_frames == {key: 3}
        kinds = [e.kind for e in rec.snapshot()]
        assert kinds.count("transport.peer.dropped") == 3
        # Running count rides the event detail.
        details = [
            e.detail
            for e in rec.snapshot()
            if e.kind == "transport.peer.dropped"
        ]
        assert details == [1, 2, 3]
        overflow_logs = [
            r
            for r in caplog.records
            if "peer backlog overflow" in r.getMessage()
        ]
        assert len(overflow_logs) == 1  # first drop only
        assert f"127.0.0.1:{dead_port}" in overflow_logs[0].getMessage()
    finally:
        node.stop()


def test_reconnect_schedule_is_deterministic_and_capped():
    # ISSUE 11 satellite: the dialer's backoff is seeded per
    # (seed, peer) — same pair, same exact ramp; different peer,
    # different jitter. Cap-before-jitter: the base delay saturates at
    # cap but the jittered spread never collapses to a fixed point.
    from itertools import islice

    from hyperdrive_tpu.transport import reconnect_schedule

    key = ("127.0.0.1", 4242)
    a = list(islice(reconnect_schedule(7, key), 8))
    b = list(islice(reconnect_schedule(7, key), 8))
    c = list(islice(reconnect_schedule(7, ("127.0.0.1", 4243)), 8))
    assert a == b
    assert a != c
    base, factor, cap, jitter = 0.05, 2.0, 2.0, 0.5
    for i, d in enumerate(a):
        lo = min(cap, base * factor ** min(i, 6))
        assert lo <= d <= lo * (1.0 + jitter)
    # Saturated: every post-cap delay stays in [cap, cap*(1+jitter)].
    assert all(cap <= d <= cap * (1.0 + jitter) for d in a[6:])


def test_backoff_ceiling_is_configurable_and_validated_eagerly():
    # The cap is a per-node spec'd bound: TcpNode(backoff={"cap": ...})
    # reshapes every dialer schedule the node creates, and a malformed
    # shaping dict fails at CONSTRUCTION (the probe draw in __init__),
    # not on the reconnect path mid-outage.
    from itertools import islice

    from hyperdrive_tpu.transport import reconnect_schedule

    key = ("127.0.0.1", 4242)
    tight = list(islice(reconnect_schedule(7, key, cap=0.2), 10))
    assert all(d <= 0.2 * 1.5 for d in tight)
    # The ramp saturates: base 0.05 doubles to 0.2 in two steps, and
    # every later delay draws from the clamped band.
    assert all(0.2 <= d for d in tight[3:])

    node = TcpNode(seed=7, backoff={"cap": 0.2, "jitter": 0.0})
    assert node.backoff == {"cap": 0.2, "jitter": 0.0}
    sched = reconnect_schedule(7, key, **node.backoff)
    assert max(islice(sched, 16)) <= 0.2

    with pytest.raises(ValueError):
        TcpNode(seed=7, backoff={"cap": 0.01})  # cap < base
    with pytest.raises(ValueError):
        TcpNode(seed=7, backoff={"base": -1.0})
    with pytest.raises(ValueError):
        TcpNode(seed=7, backoff={"factor": 0.5})
    with pytest.raises(ValueError):
        TcpNode(seed=7, backoff={"jitter": -0.1})


def test_sender_reconnects_with_backoff_and_emits_event():
    # Peer is down at first broadcast; the sender retries on the seeded
    # ramp, and when the peer comes up the frame arrives and the node
    # emits transport.reconnect with the attempt count.
    import time

    from hyperdrive_tpu.obs.recorder import Recorder

    rec = Recorder(threadsafe=True)
    node = TcpNode(obs=rec.scoped(-1), seed=3)
    (port,) = _free_ports(1)
    node.add_peer("127.0.0.1", port)
    node.start()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        pv = Prevote(
            height=1, round=0, value=b"\x05" * 32, sender=b"\x01" * 32
        )
        node.broadcast(pv)  # peer still down: dialer enters the ramp
        time.sleep(0.15)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        srv.settimeout(10.0)
        conn, _ = srv.accept()
        conn.settimeout(10.0)
        frame = encode_frame(pv)
        got = b""
        while len(got) < len(frame):
            got += conn.recv(len(frame) - len(got))
        assert got == frame
        conn.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            attempts = [
                e.detail for e in rec.snapshot()
                if e.kind == "transport.reconnect"
            ]
            if attempts:
                break
            time.sleep(0.02)
        assert attempts and attempts[0] >= 1
    finally:
        node.stop()
        srv.close()


def test_rotate_epoch_installs_tables_and_counts_stale_frames():
    # ISSUE 11 satellite: epoch handoff on the socket path. rotate_epoch
    # pushes the new table/generation to registered wire verifiers, and
    # frames from retired signatories at/after their retirement height
    # are counted (wire.frame.stale) and dropped — never fatal.
    from hyperdrive_tpu.obs.recorder import Recorder

    class FakeVerifier:
        def __init__(self):
            self.installed = None

        def install_table(self, table, generation):
            self.installed = (table, generation)

    class Sink:
        def __init__(self):
            self.prevotes = []

        def propose(self, msg, stop):
            pass

        def prevote(self, msg, stop):
            self.prevotes.append(msg)

        def precommit(self, msg, stop):
            pass

    rec = Recorder(threadsafe=True)
    node = TcpNode(obs=rec.scoped(-1))
    verifier = FakeVerifier()
    sink = Sink()
    node.register_wire_verifier(verifier)
    node.add_replica(sink)
    retired_key = b"\x0a" * 32
    try:
        node.rotate_epoch(2, table={b"\x0b" * 32: b"pk"},
                          retired={retired_key: 5})
        assert node.generation == 2
        assert verifier.installed == ({b"\x0b" * 32: b"pk"}, 2)
        # Retired sender at its first stale height: dropped, counted.
        stale = Prevote(
            height=5, round=0, value=b"\x07" * 32, sender=retired_key
        )
        node._deliver(stale, peer=("127.0.0.1", 9))
        assert node.stale_frames == 1 and sink.prevotes == []
        # The same identity BELOW the bound is still valid history.
        old = Prevote(
            height=4, round=0, value=b"\x07" * 32, sender=retired_key
        )
        node._deliver(old, peer=("127.0.0.1", 9))
        assert len(sink.prevotes) == 1
        kinds = [e.kind for e in rec.snapshot()]
        assert kinds.count("epoch.switch") == 1
        assert kinds.count("wire.frame.stale") == 1
    finally:
        node.stop()


def test_wire_admission_gates_ingress_but_not_own_broadcasts():
    # The admission gate applies to wire ingress only: a duplicated
    # inbound prevote sheds, while the node's own broadcast of the same
    # message always self-delivers.
    from hyperdrive_tpu.load import AdmissionGate, BackpressureController
    from hyperdrive_tpu.load.backpressure import SHED_DUPLICATES

    class Sink:
        def __init__(self):
            self.prevotes = []

        def propose(self, msg, stop):
            pass

        def prevote(self, msg, stop):
            self.prevotes.append(msg)

        def precommit(self, msg, stop):
            pass

    ctrl = BackpressureController(threadsafe=True)
    ctrl.floor = SHED_DUPLICATES
    ctrl.poll()
    gate = AdmissionGate(ctrl, threadsafe=True)
    node = TcpNode(admission=gate)
    sink = Sink()
    node.add_replica(sink)
    try:
        pv = Prevote(
            height=1, round=0, value=b"\x05" * 32, sender=b"\x01" * 32
        )
        peer = ("127.0.0.1", 7)
        node._deliver(pv, peer=peer)
        node._deliver(pv, peer=peer)
        assert len(sink.prevotes) == 1
        assert gate.shed == {"duplicate": 1}
        node.broadcast(pv)  # local=True path: never gated
        assert len(sink.prevotes) == 2
    finally:
        node.stop()


def test_backlog_overflow_sheds_new_prevotes_under_pressure():
    # Priority-aware outbound shedding: at SHED_LOW_PRIORITY a full
    # peer queue drops the NEW prevote frame (keeping the backlog's
    # older, higher-value frames) and counts it by class in the
    # Registry; without pressure the old evict-oldest behavior holds
    # (test_peer_backlog_overflow_counts_drops).
    from hyperdrive_tpu.load import AdmissionGate, BackpressureController
    from hyperdrive_tpu.load.backpressure import SHED_LOW_PRIORITY
    from hyperdrive_tpu.obs.metrics import Registry
    from hyperdrive_tpu.transport import _PEER_QUEUE

    registry = Registry()
    ctrl = BackpressureController(threadsafe=True)
    ctrl.floor = SHED_LOW_PRIORITY
    ctrl.poll()
    gate = AdmissionGate(ctrl, threadsafe=True)
    node = TcpNode(admission=gate, registry=registry)
    (dead_port,) = _free_ports(1)
    try:
        node.add_peer("127.0.0.1", dead_port)
        pv = Prevote(
            height=1, round=0, value=b"\x05" * 32, sender=b"\x01" * 32
        )
        for _ in range(_PEER_QUEUE + 4):
            node.broadcast(pv)
        key = ("127.0.0.1", dead_port)
        assert node.dropped_frames == {key: 4}
        shed = registry.counters["wire.frame.shed"]
        assert shed["low_priority"].value == 4
        # The queue still holds the OLDEST frames (nothing evicted).
        assert node._peer_queues[key].qsize() == _PEER_QUEUE
    finally:
        node.stop()


def test_chaos_proxy_bandwidth_throttle_pays_serialization_delay():
    # The overload family's slow-peer fault: every frame through a
    # throttled proxy pays size*8/bandwidth seconds, FIFO.
    import threading
    import time

    from hyperdrive_tpu.chaos.proxy import ChaosProxy

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    target_port = srv.getsockname()[1]
    received = []
    done = threading.Event()

    pv = Prevote(height=1, round=0, value=b"\x05" * 32, sender=b"\x01" * 32)
    frame = encode_frame(pv)

    def read_side():
        conn, _ = srv.accept()
        with conn:
            got = b""
            while len(got) < 3 * len(frame):
                chunk = conn.recv(4096)
                if not chunk:
                    break
                got += chunk
            received.append(got)
            done.set()

    reader = threading.Thread(target=read_side, daemon=True)
    reader.start()
    bps = len(frame) * 8.0 * 20  # ~50 ms per frame
    with ChaosProxy(
        "127.0.0.1", target_port, bandwidth_bps=bps
    ) as proxy:
        t0 = time.monotonic()
        with socket.create_connection(("127.0.0.1", proxy.port)) as s:
            for _ in range(3):
                s.sendall(frame)
            assert done.wait(10.0)
        elapsed = time.monotonic() - t0
        assert received[0] == frame * 3
        assert proxy.forwarded == 3
        expected = 3 * len(frame) * 8.0 / bps
        assert abs(proxy.throttled_s - expected) < 1e-9
        assert elapsed >= expected * 0.9  # the sleep actually happened
    srv.close()
