"""Engine settle fast path smoke: columnar ingest, double-buffered
(pipelined) verify, and router hysteresis — the PR's perf paths proven
state-identical to the object/serial paths on a real (small) signed
network, fast enough to run un-marked in tier 1.

The columnar/per-object state equivalence is property-tested in
test_columnar_parity.py; these tests pin the ENGINE wiring: the fast
path actually engages (tracer counters), and whole-run commit digests
are byte-identical with every fast path toggled off.
"""

from hyperdrive_tpu.harness import Simulation
from hyperdrive_tpu.ops.votegrid import CheckedTallyView


def _run(**kw):
    sim = Simulation(n=4, target_height=6, seed=11, burst=True, sign=True,
                     **kw)
    res = sim.run()
    assert res.completed
    res.assert_safety()
    return sim, res


def test_columnar_fastpath_engages_and_commits_match_object_path():
    sim_c, res_c = _run()
    sim_o, res_o = _run(columnar_ingest=False, pipeline_verify=False)
    assert res_c.commits == res_o.commits
    assert res_c.steps == res_o.steps
    fast = sim_c.tracer.snapshot()["counters"].get(
        "replica.ingest.fastpath_rows", 0
    )
    assert fast > 0
    assert sim_o.tracer.snapshot()["counters"].get(
        "replica.ingest.fastpath_rows", 0
    ) == 0


def test_pipelined_settle_engages_and_commits_match_serial():
    sim_p, res_p = _run(pipeline_verify=True)
    sim_s, res_s = _run(pipeline_verify=False)
    assert res_p.commits == res_s.commits
    assert res_p.steps == res_s.steps
    assert sim_p.tracer.snapshot()["counters"].get(
        "sim.settle.pipelined", 0
    ) > 0
    assert sim_s.tracer.snapshot()["counters"].get(
        "sim.settle.pipelined", 0
    ) == 0
    # Same verification volume either way: the pipeline reshapes the
    # schedule, never the work.
    p = sim_p.tracer.snapshot()["histograms"]["sim.verify.launch"]
    s = sim_s.tracer.snapshot()["histograms"]["sim.verify.launch"]
    assert p["count"] * p["mean"] == s["count"] * s["mean"]


def test_route_hysteresis_disengages_and_rebuilds_dirty():
    sim = Simulation(n=4, target_height=2, seed=5, burst=True,
                     device_tally=True, fused_min_window=4,
                     route_hysteresis=4)
    assert sim._grid_engaged
    for _ in range(4):
        sim._note_route(True)
    assert not sim._grid_engaged
    snap = sim.tracer.snapshot()["counters"]
    assert snap.get("sim.settle.grid_disengaged") == 1
    # Disengaged routing is a no-op for the history window.
    sim._note_route(True)
    assert not sim._grid_engaged
    # Re-engaging claims the CURRENT height with every slot dirty: votes
    # host-routed while disengaged never scattered, so a plain reset
    # would undercount — the grid only becomes authoritative next height.
    sim._reengage_grid()
    assert sim._grid_engaged
    all_slots = set(sim.vote_grid.all_slots())
    for i in range(4):
        assert sim._grid_dirty[i] == all_slots
        assert sim._grid_height[i] == sim.replicas[i].proc.current_height
    assert sim.tracer.snapshot()["counters"].get(
        "sim.settle.grid_reengaged"
    ) == 1


def test_route_hysteresis_run_drops_upkeep_and_keeps_safety():
    """Every settle of this run host-routes (fused_min_window is huge),
    so the router disengages after the hysteresis window fills and the
    tail of the run skips vote-grid upkeep entirely — commits must still
    be identical to the plain host run."""
    sim_h, res_h = _run(device_tally=True, fused_min_window=10_000,
                        route_hysteresis=4, tally_check=CheckedTallyView)
    sim_o, res_o = _run()
    assert res_h.commits == res_o.commits
    snap = sim_h.tracer.snapshot()["counters"]
    assert snap.get("sim.settle.grid_disengaged", 0) >= 1
    assert snap.get("sim.settle.grid_upkeep_skipped", 0) > 0
