"""Wire-path (device decompression) Ed25519: differential tests.

The wire kernels decompress A and R on the device; they must agree with
the host oracle bit-for-bit on every input class — including the
decompression-specific adversarial encodings the packed path never sees
on device (non-canonical y, non-residue x^2, the sign bit on x == 0).
"""

import numpy as np

import jax.numpy as jnp
import pytest

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.ops import fe25519 as fe
from hyperdrive_tpu.ops.ed25519_wire import (
    Ed25519WireHost,
    TpuWireVerifier,
    decompress_device,
    limbs_from_rows,
    make_wire_verify_fn,
)
from hyperdrive_tpu.verifier import HostVerifier

P = host_ed.P


@pytest.fixture(scope="module")
def ring():
    return KeyRing.deterministic(8, namespace=b"wiretest")


def test_minus_one_over_d_is_nonresidue():
    # The premise that makes the combined sqrt-ratio trick EXACTLY equal
    # to the oracle's x2 = u * inv(v) path: v = d*y^2 + 1 can only vanish
    # if -1/d is a square mod p. It is not — so v != 0 for every y and no
    # divergence case exists.
    t = (-pow(host_ed.D, P - 2, P)) % P
    assert pow(t, (P - 1) // 2, P) == P - 1


def _wire_verify(items, host=None):
    host = host or Ed25519WireHost(buckets=(64,))
    rows, prevalid, n = host.pack_wire(items)
    fn = make_wire_verify_fn()
    ok = np.asarray(fn(*(jnp.asarray(r) for r in rows)))
    return (ok & prevalid)[:n]


def _oracle(items):
    return [host_ed.verify(p, m, s) for p, m, s in items]


def test_wire_matches_oracle_valid_and_corrupted(ring, rng):
    items = []
    for _i in range(24):
        kp = ring[rng.randrange(len(ring))]
        msg = rng.randbytes(rng.randint(0, 64))
        sig = host_ed.sign(kp.seed, msg)
        roll = rng.random()
        if roll < 0.3:
            sig = bytearray(sig)
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(sig)
        elif roll < 0.4:
            msg = msg + b"x"
        items.append((kp.public, msg, sig))
    got = _wire_verify(items).tolist()
    assert got == _oracle(items)


def test_wire_adversarial_decompression_cases(ring):
    kp = ring[0]
    msg = b"wire adversarial"
    sig = host_ed.sign(kp.seed, msg)

    def enc(y, sign):
        return int.to_bytes(y | (sign << 255), 32, "little")

    identity = enc(1, 0)  # (0, 1): x == 0, sign 0 -> decodes
    zero_sign = enc(1, 1)  # x == 0 with sign bit -> oracle rejects
    y_zero = enc(0, 0)  # x^2 = -1: a residue -> decodes to (sqrt(-1), 0)
    noncanon_p = enc(P, 0)  # y == p: non-canonical -> reject
    noncanon_max = enc((1 << 255) - 1, 0)  # y > p -> reject
    # Scan for a y whose x^2 is a non-residue (rejects in _recover_x).
    nonres = None
    for y in range(2, 50):
        if host_ed.point_decompress(enc(y, 0)) is None:
            nonres = enc(y, 0)
            break
    assert nonres is not None
    s_big = sig[:32] + int.to_bytes(
        int.from_bytes(sig[32:], "little") + host_ed.L, 32, "little"
    )  # s >= L

    cases = [
        (kp.public, msg, sig),  # control: valid
        # R replaced by each crafted encoding:
        (kp.public, msg, identity + sig[32:]),
        (kp.public, msg, zero_sign + sig[32:]),
        (kp.public, msg, y_zero + sig[32:]),
        (kp.public, msg, noncanon_p + sig[32:]),
        (kp.public, msg, noncanon_max + sig[32:]),
        (kp.public, msg, nonres + sig[32:]),
        # A replaced by each crafted encoding:
        (identity, msg, sig),
        (zero_sign, msg, sig),
        (y_zero, msg, sig),
        (noncanon_p, msg, sig),
        (nonres, msg, sig),
        # scalar range:
        (kp.public, msg, s_big),
        # wrong lengths:
        (kp.public[:31], msg, sig),
        (kp.public, msg, sig[:63]),
    ]
    got = _wire_verify(cases).tolist()
    want = _oracle(cases)
    assert got == want
    assert want[0] is True and not any(want[1:])


def test_decompress_device_matches_oracle(ring, rng):
    # Valid compressed points (both parities), the edge encodings above,
    # and random byte strings: decompress_device must agree with
    # point_decompress on validity AND on the recovered x.
    encs = []
    for i in range(12):
        kp = ring[i % len(ring)]
        pt = host_ed.point_decompress(kp.public)
        x, y = pt[0], pt[1]
        encs.append(int.to_bytes(y | ((x & 1) << 255), 32, "little"))
        encs.append(int.to_bytes(y | (((x & 1) ^ 1) << 255), 32, "little"))
    encs += [int.to_bytes(1, 32, "little"), int.to_bytes(0, 32, "little")]
    encs += [rng.randbytes(32) for _ in range(24)]
    # Filter to canonical y (the packer's precondition — non-canonical
    # encodings never reach the device).
    encs = [
        e
        for e in encs
        if (int.from_bytes(e, "little") & ((1 << 255) - 1)) < P
    ]
    rows = jnp.asarray(
        np.frombuffer(b"".join(encs), dtype=np.uint8).reshape(len(encs), 32)
    )
    y_limbs, sign = limbs_from_rows(rows)
    x_dev, ok_dev = decompress_device(y_limbs, sign)
    x_dev = np.asarray(fe.canonical(x_dev))
    ok_dev = np.asarray(ok_dev)
    for i, e in enumerate(encs):
        want = host_ed.point_decompress(e)
        assert bool(ok_dev[i]) == (want is not None), e.hex()
        if want is not None:
            assert fe.from_limbs(x_dev[i]) == want[0], e.hex()


def test_pack_wire_native_matches_python(ring, rng):
    from hyperdrive_tpu import native

    if native.instance() is None:
        pytest.skip("native runtime unavailable")
    items = []
    for i in range(40):
        kp = ring[i % len(ring)]
        msg = rng.randbytes(rng.randint(0, 48))
        sig = host_ed.sign(kp.seed, msg)
        roll = rng.random()
        if roll < 0.2:
            sig = b"\xff" * 64  # non-canonical R (and s >= L)
        elif roll < 0.3:
            sig = sig[:32] + int.to_bytes(
                int.from_bytes(sig[32:], "little") + host_ed.L,
                32,
                "little",
            )
        elif roll < 0.4:
            items.append((kp.public[:16], msg, sig))  # bad length
            continue
        items.append((kp.public, msg, sig))
    nat = Ed25519WireHost(buckets=(64,))
    assert nat._native is not None
    py = Ed25519WireHost(buckets=(64,), use_native=False)
    rows_n, pv_n, n_n = nat.pack_wire(items)
    rows_p, pv_p, n_p = py.pack_wire(items)
    assert n_n == n_p
    assert (pv_n == pv_p).all()
    for a, b in zip(rows_n, rows_p):
        assert (a == b).all()


def test_wire_pallas_matches_xla_and_oracle(ring, rng):
    from hyperdrive_tpu.ops.ed25519_pallas import wire_verify_pallas

    items = []
    for i in range(64):
        kp = ring[i % len(ring)]
        msg = rng.randbytes(32)
        sig = host_ed.sign(kp.seed, msg)
        kind = i % 4
        if kind == 1:
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
        elif kind == 2:
            msg = rng.randbytes(32)
            items.append((kp.public, msg, host_ed.sign(kp.seed, rng.randbytes(32))))
            continue
        elif kind == 3 and i % 8 == 3:
            sig = b"\xff" * 64
        items.append((kp.public, msg, sig))
    host = Ed25519WireHost(buckets=(64,))
    rows, prevalid, n = host.pack_wire(items)
    dev_in = tuple(jnp.asarray(r) for r in rows)
    xla = np.asarray(make_wire_verify_fn()(*dev_in)) & prevalid
    pl = (
        np.asarray(wire_verify_pallas(*dev_in, block=64, interpret=True))
        & prevalid
    )
    assert (pl == xla).all()
    assert xla[:n].tolist() == _oracle(items)


def test_semiwire_indexed_matches_oracle(ring, rng):
    from hyperdrive_tpu.ops.ed25519_wire import (
        ValidatorTable,
        make_semiwire_verify_fn,
    )

    # Table includes one pubkey that is NOT a valid curve point: the
    # oracle rejects anything "signed" by it, and the table's valid mask
    # must do the same.
    bogus = b"\xff" * 32
    pubs = [kp.public for kp in (ring[i] for i in range(len(ring)))] + [bogus]
    table = ValidatorTable(pubs)
    host = Ed25519WireHost(buckets=(64,))
    items = []
    for i in range(30):
        kp = ring[i % len(ring)]
        msg = rng.randbytes(32)
        sig = host_ed.sign(kp.seed, msg)
        if i % 5 == 1:
            sig = bytearray(sig)
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(sig)
        elif i % 5 == 2:
            items.append((bogus, msg, sig))
            continue
        items.append((kp.public, msg, sig))
    rows, prevalid, n = host.pack_wire_indexed(items, table)
    ok = np.asarray(
        make_semiwire_verify_fn()(
            *(jnp.asarray(r) for r in rows), *table.arrays()
        )
    )
    got = (ok & prevalid)[:n].tolist()
    assert got == _oracle(items)


def test_semiwire_pallas_matches_xla(ring, rng):
    from hyperdrive_tpu.ops.ed25519_pallas import semiwire_verify_pallas
    from hyperdrive_tpu.ops.ed25519_wire import (
        ValidatorTable,
        make_semiwire_verify_fn,
    )

    table = ValidatorTable([ring[i].public for i in range(len(ring))])
    host = Ed25519WireHost(buckets=(64,))
    items = []
    for i in range(64):
        kp = ring[i % len(ring)]
        msg = rng.randbytes(32)
        sig = host_ed.sign(kp.seed, msg)
        if i % 3 == 1:
            sig = sig[:33] + bytes([sig[33] ^ 4]) + sig[34:]
        elif i % 7 == 2:
            sig = b"\xff" * 64  # prevalid False (bad R, s >= L)
        items.append((kp.public, msg, sig))
    rows, prevalid, n = host.pack_wire_indexed(items, table)
    dev_in = tuple(jnp.asarray(r) for r in rows)
    xla = np.asarray(make_semiwire_verify_fn()(*dev_in, *table.arrays()))
    pl = np.asarray(
        semiwire_verify_pallas(
            *dev_in, *table.arrays(), block=64, interpret=True
        )
    )
    assert (pl == xla).all()
    assert (xla & prevalid)[:n].tolist() == _oracle(items)


def test_table_verifier_falls_back_on_unknown_pub(ring):
    from hyperdrive_tpu.ops.ed25519_wire import ValidatorTable

    # ring[7] is NOT in the table: the chunk must route through the full
    # wire path and still match the oracle (verdicts independent of the
    # table).
    table = ValidatorTable([ring[i].public for i in range(4)])
    wv = TpuWireVerifier(buckets=(16,), table=table)
    items = []
    for i in (0, 1, 2, 3, 7):
        kp = ring[i]
        msg = bytes([i]) * 20
        items.append((kp.public, msg, host_ed.sign(kp.seed, msg)))
    assert wv.verify_signatures(items).tolist() == _oracle(items)
    # All-known chunk rides the indexed path, same verdicts.
    known = items[:4]
    assert wv.verify_signatures(known).tolist() == _oracle(known)


def test_wire_verifier_protocol_matches_host(ring):
    hv = HostVerifier()
    wv = TpuWireVerifier(buckets=(16, 64))
    msgs = []
    for i in range(6):
        kp = ring[i]
        pv = Prevote(height=1, round=0, value=bytes([i]) * 32, sender=kp.public)
        if i % 3 == 0:
            msgs.append(kp.sign_message(pv))
        elif i % 3 == 1:
            msgs.append(pv)  # unsigned
        else:
            msgs.append(pv.with_signature(b"\x02" * 64))
    assert wv.verify_batch(msgs) == hv.verify_batch(msgs)
    assert wv.verify_signatures([]).tolist() == []


def test_wire_chunking_across_buckets(ring):
    # 5 items in a 4-bucket verifier: two launches, one concatenated
    # fetch; verdicts must still line up with the oracle.
    wv = TpuWireVerifier(buckets=(2, 4))
    items = []
    for i in range(5):
        kp = ring[i % len(ring)]
        msg = bytes([i]) * 16
        sig = host_ed.sign(kp.seed, msg)
        if i == 2:
            sig = b"\x00" * 64
        items.append((kp.public, msg, sig))
    assert wv.verify_signatures(items).tolist() == _oracle(items)
