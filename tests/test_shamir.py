"""Shamir sharing: host roundtrips, threshold properties, device parity."""

import pytest

from hyperdrive_tpu.crypto import shamir
from hyperdrive_tpu.crypto.ed25519 import P
from hyperdrive_tpu.ops.shamir import BatchReconstructor


def test_block_roundtrip(rng):
    for _ in range(10):
        secret = rng.getrandbits(248)
        shares = shamir.split_block(secret, k=3, n=5, tag=b"t")
        assert len(shares) == 5
        # Any 3 shares reconstruct.
        subset = rng.sample(shares, 3)
        assert shamir.reconstruct_block(subset) == secret


def test_below_threshold_gives_wrong_secret(rng):
    secret = rng.getrandbits(200)
    shares = shamir.split_block(secret, k=3, n=5, tag=b"t2")
    # 2 shares interpolate a line — almost surely not the secret.
    assert shamir.reconstruct_block(shares[:2]) != secret


def test_k_equals_one_is_replication():
    shares = shamir.split_block(42, k=1, n=4)
    assert all(y == 42 for _, y in shares)


def test_payload_roundtrip(rng):
    for size in (0, 1, 30, 31, 32, 100):
        payload = rng.randbytes(size)
        blocks = shamir.split_payload(payload, k=3, n=5, tag=b"p")
        subset = [rng.sample(b, 3) for b in blocks]
        assert shamir.reconstruct_payload(subset) == payload


def test_invalid_inputs():
    with pytest.raises(ValueError):
        shamir.split_block(P, 2, 3)
    with pytest.raises(ValueError):
        shamir.split_block(1, 4, 3)


def test_device_matches_host(rng):
    recon = BatchReconstructor()
    payload = rng.randbytes(200)
    blocks = shamir.split_payload(payload, k=4, n=7, tag=b"dev")
    # Pick the same 4 shares for every block (as a real quorum would).
    idx = sorted(rng.sample(range(7), 4))
    subset = [[b[i] for i in idx] for b in blocks]
    host = shamir.reconstruct_payload(subset)
    dev = recon.reconstruct_payload_shares(subset)
    assert host == dev == payload


def test_device_block_batch(rng):
    recon = BatchReconstructor()
    secrets = [rng.getrandbits(240) for _ in range(16)]
    k, n = 3, 5
    all_shares = [shamir.split_block(s, k, n, tag=bytes([i])) for i, s in enumerate(secrets)]
    xs = [1, 3, 5]
    y_blocks = [[sh[x - 1][1] for sh in all_shares] for x in xs]
    got = recon.reconstruct_blocks(xs, y_blocks)
    assert got == secrets


def test_share_bundle_round_trip(rng):
    payload = rng.randbytes(100)
    blocks = shamir.split_payload(payload, k=3, n=5, tag=b"bundle")
    data = shamir.encode_share_bundle(blocks)
    back = shamir.decode_share_bundle(data)
    assert back == blocks
    assert shamir.reconstruct_payload([b[:3] for b in back]) == payload


def test_share_bundle_malformed_inputs_raise(rng):
    import pytest

    blocks = shamir.split_payload(b"x" * 40, k=2, n=3, tag=b"m")
    data = shamir.encode_share_bundle(blocks)
    for bad in (
        b"",                       # too short
        data[:-1],                 # truncated
        data + b"\x00",            # trailing junk
        b"\xff\xff\xff\xff" + data[4:],  # absurd block count
        data[:8] + b"\xff" * 32 + data[40:],  # share >= p
    ):
        with pytest.raises(ValueError):
            shamir.decode_share_bundle(bad)


def test_adaptive_reconstructor_small_batch_stays_on_host():
    from hyperdrive_tpu.crypto import shamir as host_shamir
    from hyperdrive_tpu.ops.shamir import AdaptiveReconstructor

    payload = bytes(range(62))  # 2 blocks << crossover
    blocks = host_shamir.split_payload(payload, 3, 4, tag=b"ad1")
    subset = [shares[:3] for shares in blocks]
    ad = AdaptiveReconstructor()
    assert ad.reconstruct_payload_shares(subset) == payload
    # The device path was never launched: no Lagrange weights cached.
    assert not ad.device._lam_cache
    assert ad.reconstruct_payload_shares([]) == b""


def test_adaptive_reconstructor_calibrates_and_routes():
    import secrets as pysecrets

    from hyperdrive_tpu.crypto import shamir as host_shamir
    from hyperdrive_tpu.ops.shamir import AdaptiveReconstructor

    k, n = 3, 4
    wide = pysecrets.token_bytes(31 * 32)
    blocks = host_shamir.split_payload(wide, k, n, tag=b"ad2")
    subset = [shares[:k] for shares in blocks]
    ad = AdaptiveReconstructor(calibrate_at=32)
    # First wide batch triggers calibration: both paths timed AND
    # cross-checked; the result is correct either way.
    assert ad.reconstruct_payload_shares(subset) == wide
    assert ad.calibrated
    assert set(ad.rates) == {
        "host_blocks_per_s", "device_blocks_per_s", "device_overhead_s"
    }
    assert ad.crossover_blocks > 0
    # Post-calibration routing still returns oracle-equal results on both
    # sides of the crossover.
    small = bytes(range(31))
    sb = host_shamir.split_payload(small, k, n, tag=b"ad3")
    assert ad.reconstruct_payload_shares(
        [s[:k] for s in sb]
    ) == small
    ad.crossover_blocks = 1  # force the device leg
    assert ad.reconstruct_payload_shares(subset) == wide
