"""Differential tests: native C++ host runtime vs the pure-Python oracle.

The native packer (hyperdrive_tpu/native/hd_native.cc) must produce
bit-identical tensors and prevalidity masks to the Python packing loop in
``Ed25519BatchHost`` for every input class: valid signatures, malformed
points, non-canonical encodings, out-of-range scalars, and wrong-length
fields.
"""

import hashlib
import os
import random

import numpy as np
import pytest

from hyperdrive_tpu.crypto import ed25519 as ed
from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost

native = pytest.importorskip("hyperdrive_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


@pytest.fixture(scope="module")
def packer():
    p = native.NativePacker()
    p.cache_clear()
    return p


def _keypair(i: int):
    seed = hashlib.sha256(b"native-test-%d" % i).digest()
    return seed, ed.public_key_from_seed(seed)


def test_sha512_matches_hashlib(packer):
    rng = random.Random(1)
    for n in [0, 1, 63, 64, 111, 112, 127, 128, 129, 300, 1000]:
        data = bytes(rng.randrange(256) for _ in range(n))
        assert packer.sha512(data) == hashlib.sha512(data).digest()


def test_mod_l_matches_python(packer):
    rng = random.Random(2)
    cases = [b"\x00" * 64, b"\xff" * 64]
    cases += [bytes(rng.randrange(256) for _ in range(64)) for _ in range(200)]
    # Values straddling multiples of L.
    for m in (1, 2, 7, 1 << 200):
        for delta in (-1, 0, 1):
            v = (ed.L * m + delta) % (1 << 512)
            cases.append(v.to_bytes(64, "little"))
    for data in cases:
        assert packer.mod_l(data) == int.from_bytes(data, "little") % ed.L


def test_decompress_matches_python(packer):
    rng = random.Random(3)
    cases = []
    for i in range(20):
        _, pub = _keypair(i)
        cases.append(pub)
        # Flip the sign bit: usually still a valid (negated) point.
        cases.append(bytes([*pub[:31], pub[31] ^ 0x80]))
    # Edge encodings: y = 0, 1, 2, p-1, p, p+1, 2^255-1, and random blobs.
    for y in (0, 1, 2, ed.P - 1, ed.P, ed.P + 1, (1 << 255) - 1):
        for sign in (0, 1):
            cases.append((y | (sign << 255)).to_bytes(32, "little"))
    cases += [bytes(rng.randrange(256) for _ in range(32)) for _ in range(300)]

    for data in cases:
        ref = ed.point_decompress(data)
        got = packer.decompress(data)
        if ref is None:
            assert got is None, data.hex()
        else:
            assert got == (ref[0], ref[1]), data.hex()


def _pack_both(items):
    py = Ed25519BatchHost(use_native=False)
    cc = Ed25519BatchHost(use_native=True)
    assert cc._native is not None, "native packer should be active"
    a_py, v_py, n_py = py.pack(items)
    a_cc, v_cc, n_cc = cc.pack(items)
    return (a_py, v_py, n_py), (a_cc, v_cc, n_cc)


def test_pack_batch_differential(packer):
    rng = random.Random(4)
    items = []
    # Valid signatures.
    for i in range(12):
        seed, pub = _keypair(i)
        digest = hashlib.sha256(b"msg-%d" % i).digest()
        items.append((pub, digest, ed.sign(seed, digest)))
    # Corrupted signatures (wrong digest — packs fine, verifies false).
    seed, pub = _keypair(100)
    digest = hashlib.sha256(b"real").digest()
    sig = ed.sign(seed, digest)
    items.append((pub, hashlib.sha256(b"fake").digest(), sig))
    # Malformed R (not a point).
    items.append((pub, digest, b"\x13" * 32 + sig[32:]))
    # s >= L.
    big_s = (ed.L).to_bytes(32, "little")
    items.append((pub, digest, sig[:32] + big_s))
    # s just below L (packs fine).
    ok_s = (ed.L - 1).to_bytes(32, "little")
    items.append((pub, digest, sig[:32] + ok_s))
    # Malformed pubkey.
    items.append((b"\xff" * 32, digest, sig))
    # Wrong lengths.
    items.append((pub[:31], digest, sig))
    items.append((pub, digest[:16], sig))
    items.append((pub, digest, sig[:63]))
    items.append((b"", b"", b""))
    # Random garbage.
    for _ in range(20):
        items.append(
            (
                bytes(rng.randrange(256) for _ in range(32)),
                bytes(rng.randrange(256) for _ in range(32)),
                bytes(rng.randrange(256) for _ in range(64)),
            )
        )

    (a_py, v_py, n_py), (a_cc, v_cc, n_cc) = _pack_both(items)
    assert n_py == n_cc == len(items)
    np.testing.assert_array_equal(v_py, v_cc)
    for name, x, y in zip(
        ["ax", "ay", "at", "rx", "ry", "s_nib", "k_nib"], a_py, a_cc
    ):
        np.testing.assert_array_equal(x, y, err_msg=name)


def test_pack_cache_is_correct_across_batches(packer):
    # The pubkey cache must not confuse distinct keys or leak staleness.
    items1, items2 = [], []
    for i in range(8):
        seed, pub = _keypair(200 + i)
        d = hashlib.sha256(b"a%d" % i).digest()
        items1.append((pub, d, ed.sign(seed, d)))
        d2 = hashlib.sha256(b"b%d" % i).digest()
        items2.append((pub, d2, ed.sign(seed, d2)))
    (a_py1, v_py1, _), (a_cc1, v_cc1, _) = _pack_both(items1)
    (a_py2, v_py2, _), (a_cc2, v_cc2, _) = _pack_both(items2)
    np.testing.assert_array_equal(v_py1, v_cc1)
    np.testing.assert_array_equal(v_py2, v_cc2)
    for x, y in zip(a_py1 + a_py2, a_cc1 + a_cc2):
        np.testing.assert_array_equal(x, y)


def test_env_var_disables_native():
    # load() caches module-globally, so the kill switch must be probed in a
    # fresh interpreter.
    import subprocess
    import sys

    code = (
        "import hyperdrive_tpu.native as n; assert not n.available(); "
        "from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost; "
        "assert Ed25519BatchHost()._native is None"
    )
    env = dict(os.environ, HD_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr


def test_use_native_false_skips_native():
    host = Ed25519BatchHost(use_native=False)
    assert host._native is None


# --------------------------------------------------- sign / verify parity


def test_sign_and_public_match_oracle(packer):
    rng = random.Random(11)
    for i in range(8):
        seed = hashlib.sha256(b"sp%d" % i).digest()
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 80)))
        assert packer.public_from_seed(seed) == ed.public_key_from_seed(seed)
        assert packer.sign(seed, msg) == ed.sign(seed, msg)


def test_verify_one_matches_oracle(packer):
    rng = random.Random(12)
    seed = hashlib.sha256(b"vo").digest()
    pub = ed.public_key_from_seed(seed)
    msg = b"the vote digest"
    sig = ed.sign(seed, msg)
    cases = [
        (pub, msg, sig, True),
        (pub, msg + b"!", sig, False),
        (pub, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:], False),
        (pub, msg, bytes([sig[0] ^ 1]) + sig[1:], False),
        (b"\xff" * 32, msg, sig, False),
        (pub, msg, sig[:32] + (ed.L).to_bytes(32, "little"), False),
    ]
    for p_, m, s, want in cases:
        assert packer.verify(p_, m, s) == want
        assert ed.verify(p_, m, s) == want
    # Random garbage agreement.
    for _ in range(30):
        p_ = bytes(rng.randrange(256) for _ in range(32))
        s = bytes(rng.randrange(256) for _ in range(64))
        assert packer.verify(p_, msg, s) == ed.verify(p_, msg, s)


def test_verify_batch_matches_singles(packer):
    seeds = [hashlib.sha256(b"vb%d" % i).digest() for i in range(6)]
    items = []
    for i, seed in enumerate(seeds):
        pub = ed.public_key_from_seed(seed)
        msg = hashlib.sha256(b"payload%d" % i).digest()
        sig = ed.sign(seed, msg)
        if i % 3 == 2:  # corrupt every third
            sig = sig[:40] + bytes([sig[40] ^ 0xFF]) + sig[41:]
        items.append((pub, msg, sig))
    items.append((b"short", b"msg", b"sig"))  # malformed lengths
    mask = packer.verify_batch(items)
    expect = [ed.verify(p_, m, s) for p_, m, s in items]
    assert mask.tolist() == expect


def test_host_verifier_uses_native_and_agrees():
    from hyperdrive_tpu.crypto.keys import KeyPair
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.verifier import HostVerifier

    kp = KeyPair.deterministic(b"hv-native")
    good = kp.sign_message(
        Prevote(height=1, round=0, value=b"\x01" * 32, sender=kp.public)
    )
    bad = Prevote(
        height=1, round=0, value=b"\x02" * 32, sender=kp.public
    ).with_signature(b"\x00" * 64)
    unsigned = Prevote(height=1, round=0, value=b"\x03" * 32, sender=kp.public)
    hv = HostVerifier()
    assert hv._native is not None
    assert hv.verify_batch([good, bad, unsigned]) == [True, False, False]
    # Python fallback agrees.
    hv._native = None
    assert hv.verify_batch([good, bad, unsigned]) == [True, False, False]
