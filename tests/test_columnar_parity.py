"""Columnar settle fast path: property-based parity with the object path.

`Process.ingest_insert_cols` (hyperdrive_tpu/process.py) ingests verified
window rows straight from a `WindowColumns` view — message objects
materialize only for rows the automaton accepts or that trip a catcher.
Its contract is BYTE-IDENTICAL automaton state to the per-object
`Process.ingest_insert` over the pre-filtered window, for every window
the engine can see: duplicates, equivocating (double-vote) rows,
Byzantine strangers, wrong heights, negative/huge rounds, proposes with
every valid_round shape, and arbitrary keep/allowed masks.

hypothesis is not a dependency of this repo, so the property loop is a
seeded `random.Random` sweep (the same discipline as testutil's
reference-mirroring generators): many windows per seed, several seeds,
every failure reproducible from the printed seed.
"""

import random

import pytest

from hyperdrive_tpu.batch import MessageBlock, WindowColumns
from hyperdrive_tpu.codec import Writer
from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.process import Process
from hyperdrive_tpu.testutil import (
    CatcherCallbacks,
    random_height,
    random_propose,
    random_signatory,
    random_value,
)
from hyperdrive_tpu.types import INVALID_ROUND

WHOAMI = b"\x01" * 32
VOTES = (Prevote, Precommit)


def _window(rng):
    """One adversarial window: a small sender/value pool (so duplicates
    and equivocations are frequent), salted with strangers, wrong
    heights, hostile rounds, and proposes."""
    senders = [random_signatory(rng) for _ in range(6)]
    values = [random_value(rng) for _ in range(3)]
    msgs = []
    n = rng.randint(0, 90)
    while len(msgs) < n:
        roll = rng.random()
        if roll < 0.55:
            kind = VOTES[rng.randrange(2)]
            msgs.append(kind(height=1, round=rng.randrange(4),
                             value=values[rng.randrange(3)],
                             sender=senders[rng.randrange(6)]))
        elif roll < 0.70 and msgs:
            # Exact duplicate of an earlier row (same object).
            msgs.append(msgs[rng.randrange(len(msgs))])
        elif roll < 0.80:
            if rng.random() < 0.5:
                msgs.append(random_propose(rng))
            else:
                msgs.append(Propose(
                    height=1, round=rng.randrange(4),
                    valid_round=rng.choice([INVALID_ROUND, 0, 1]),
                    value=values[rng.randrange(3)],
                    sender=senders[rng.randrange(6)],
                ))
        elif roll < 0.90:
            # Wrong heights and hostile round numbers.
            kind = VOTES[rng.randrange(2)]
            msgs.append(kind(
                height=rng.choice([0, 2, 5, random_height(rng)]),
                round=rng.choice([INVALID_ROUND, 0, 7, 200]),
                value=random_value(rng),
                sender=senders[rng.randrange(6)],
            ))
        else:
            # Byzantine stranger: never in the allowed set's core pool.
            kind = VOTES[rng.randrange(2)]
            msgs.append(kind(height=1, round=rng.randrange(4),
                             value=random_value(rng),
                             sender=random_signatory(rng)))
    return msgs, senders


def _build(events):
    """A Process whose catcher appends every equivocation to ``events``
    — call ORDER is part of the parity contract."""
    catcher = CatcherCallbacks(
        on_double_propose=lambda n, e: events.append(("dpp", n, e)),
        on_double_prevote=lambda n, e: events.append(("dpv", n, e)),
        on_double_precommit=lambda n, e: events.append(("dpc", n, e)),
    )
    return Process(WHOAMI, f=2, catcher=catcher)


def _marshal(st) -> bytes:
    w = Writer()
    st.marshal(w)
    return w.data()


def _assert_parity(msgs, keep, allowed, cols, label):
    obj_events, col_events = [], []
    obj_accepted, col_accepted = [], []
    p_obj = _build(obj_events)
    p_col = _build(col_events)

    filtered = [
        m for i, m in enumerate(msgs)
        if (keep is None or keep[i])
        and (allowed is None or m.sender in allowed)
    ]
    plan_obj = p_obj.ingest_insert(
        filtered, on_accepted=lambda m, pc: obj_accepted.append((m, pc))
    )
    plan_col, ingested = p_col.ingest_insert_cols(
        cols, keep=keep, allowed=allowed,
        on_accepted=lambda m, pc: col_accepted.append((m, pc)),
    )

    assert ingested == len(filtered), label
    assert plan_col == plan_obj, label
    assert col_accepted == obj_accepted, label
    assert col_events == obj_events, label
    assert p_col.state == p_obj.state, label
    # Checkpoint-byte parity: the columnar path must not leave behind
    # even an EMPTY log dict the object path would not have created
    # (e.g. for a run whose every row was filtered out).
    assert _marshal(p_col.state) == _marshal(p_obj.state), label


@pytest.mark.parametrize("seed", range(8))
def test_columnar_ingest_matches_object_path(seed):
    rng = random.Random(0xC01 + seed)
    for case in range(25):
        msgs, senders = _window(rng)
        label = f"seed={seed} case={case}"

        roll = rng.random()
        if roll < 0.4:
            keep = None
        else:
            keep = [rng.random() < 0.8 for _ in msgs]
        if rng.random() < 0.5:
            allowed = None
        else:
            # Core pool + every stranger half the time, else core only
            # (strangers then hit the allowed filter, not the logs).
            allowed = set(senders)
            if rng.random() < 0.5:
                allowed.update(m.sender for m in msgs)

        _assert_parity(
            msgs, keep, allowed, WindowColumns.from_messages(msgs), label
        )


@pytest.mark.parametrize("seed", range(4))
def test_columnar_ingest_from_wire_block_matches_object_path(seed):
    """The same property through the WIRE shape: MessageBlock rows →
    columns() → ingest, against objects materialized from the identical
    block — the deployment fast path (`DeviceTallyFlusher.settle_block`).
    """
    rng = random.Random(0xB10C + seed)
    for case in range(12):
        msgs, senders = _window(rng)
        label = f"seed={seed} case={case}"
        try:
            block = MessageBlock.from_messages(msgs)
        except (TypeError, ValueError, OverflowError):
            # Not every adversarial window is wire-batchable (e.g. u64
            # wrap-parity heights overflow the row dtype); the columnar
            # contract only covers windows the wire can carry.
            continue
        keep = None if rng.random() < 0.5 else \
            [rng.random() < 0.8 for _ in msgs]
        _assert_parity(block.to_messages(), keep, None, block.columns(),
                       label)


def test_fully_filtered_run_leaves_no_empty_logs():
    """Regression pin for the lazy-view rule: a (kind, height, round) run
    whose every row is filtered by keep must not fetch views — the
    object path never creates the round's log dict, so neither may the
    columnar path (it would change checkpoint bytes)."""
    s1, s2 = b"\x0a" * 32, b"\x0b" * 32
    v = b"\x33" * 32
    msgs = [
        Prevote(height=1, round=0, value=v, sender=s1),
        Prevote(height=1, round=3, value=v, sender=s1),
        Prevote(height=1, round=3, value=v, sender=s2),
        Precommit(height=1, round=5, value=v, sender=s2),
    ]
    keep = [True, False, False, True]
    _assert_parity(msgs, keep, None, WindowColumns.from_messages(msgs),
                   "fully-filtered run")
    p = _build([])
    p.ingest_insert_cols(WindowColumns.from_messages(msgs), keep=keep)
    assert 3 not in p.state.prevote_logs
    assert 0 in p.state.prevote_logs and 5 in p.state.precommit_logs
