"""Ed25519 host path: RFC 8032 test vectors, roundtrips, rejection cases."""

import pytest

from hyperdrive_tpu.crypto import ed25519
from hyperdrive_tpu.crypto.keys import KeyPair, KeyRing

# RFC 8032 section 7.1 test vectors.
VECTORS = [
    # (seed, public, message, signature)
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", VECTORS)
def test_rfc8032_public_key_derivation(seed, pub, msg, sig):
    assert ed25519.public_key_from_seed(bytes.fromhex(seed)).hex() == pub


@pytest.mark.parametrize("seed,pub,msg,sig", VECTORS)
def test_rfc8032_signatures(seed, pub, msg, sig):
    got = ed25519.sign(bytes.fromhex(seed), bytes.fromhex(msg))
    assert got.hex() == sig


@pytest.mark.parametrize("seed,pub,msg,sig", VECTORS)
def test_rfc8032_verification(seed, pub, msg, sig):
    assert ed25519.verify(
        bytes.fromhex(pub), bytes.fromhex(msg), bytes.fromhex(sig)
    )


def test_sign_verify_roundtrip(rng):
    for _ in range(5):
        seed = rng.randbytes(32)
        pub = ed25519.public_key_from_seed(seed)
        msg = rng.randbytes(rng.randint(0, 100))
        sig = ed25519.sign(seed, msg)
        assert ed25519.verify(pub, msg, sig)


def test_modified_message_rejected(rng):
    seed = rng.randbytes(32)
    pub = ed25519.public_key_from_seed(seed)
    sig = ed25519.sign(seed, b"hello")
    assert not ed25519.verify(pub, b"hellp", sig)


def test_modified_signature_rejected(rng):
    seed = rng.randbytes(32)
    pub = ed25519.public_key_from_seed(seed)
    sig = bytearray(ed25519.sign(seed, b"hello"))
    sig[0] ^= 1
    assert not ed25519.verify(pub, b"hello", bytes(sig))


def test_wrong_key_rejected(rng):
    seed = rng.randbytes(32)
    other = ed25519.public_key_from_seed(rng.randbytes(32))
    sig = ed25519.sign(seed, b"hello")
    assert not ed25519.verify(other, b"hello", sig)


def test_high_s_rejected(rng):
    # Malleability guard: s >= L must be rejected (RFC 8032 5.1.7).
    seed = rng.randbytes(32)
    pub = ed25519.public_key_from_seed(seed)
    sig = ed25519.sign(seed, b"m")
    s = int.from_bytes(sig[32:], "little")
    forged = sig[:32] + int.to_bytes(s + ed25519.L, 32, "little")
    assert not ed25519.verify(pub, b"m", forged)


def test_invalid_point_rejected():
    assert not ed25519.verify(b"\xff" * 32, b"m", b"\x00" * 64)
    assert ed25519.point_decompress(b"\xff" * 32) is None


def test_malformed_lengths_rejected():
    assert not ed25519.verify(b"\x00" * 31, b"m", b"\x00" * 64)
    assert not ed25519.verify(b"\x00" * 32, b"m", b"\x00" * 63)


def test_keypair_and_keyring():
    ring = KeyRing.deterministic(4)
    assert len(ring) == 4
    assert len(set(ring.signatories)) == 4
    kp = ring[0]
    assert ring.by_signatory[kp.public] is kp
    # Deterministic: same tag, same key.
    assert KeyPair.deterministic(b"hyperdrive-0").public == kp.public


def test_signed_consensus_message_verifies():
    from hyperdrive_tpu.messages import Prevote
    from hyperdrive_tpu.verifier import HostVerifier, NullVerifier

    ring = KeyRing.deterministic(2)
    pv = Prevote(height=1, round=0, value=b"\x01" * 32, sender=ring[0].public)
    signed = ring[0].sign_message(pv)
    hv = HostVerifier()
    assert hv.verify_batch([signed]) == [True]
    # Unsigned or wrong-sender messages fail.
    assert hv.verify_batch([pv]) == [False]
    imposter = Prevote(height=1, round=0, value=b"\x01" * 32,
                       sender=ring[1].public).with_signature(signed.signature)
    assert hv.verify_batch([imposter]) == [False]
    assert NullVerifier().verify_batch([pv, signed]) == [True, True]
