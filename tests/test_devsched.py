"""Async device-work queue: futures, coalescing, height pipelining.

Deterministic property tests for hyperdrive_tpu/devsched under the
sim's virtual clock — per-submitter FIFO, coalescing determinism at a
fixed seed, future fan-out, drain-on-shutdown — plus the headline
guarantee: a pipelined run commits exactly the chain the sequential
run does, and a forged-but-well-formed signature fails LOUDLY
(SpeculationMismatch) before any gated commit finalizes.

Everything here is jax-free: queue mechanics use counting launchers,
and the sim runs use the HostVerifier leg (``sign=True``).
"""

import pytest

from hyperdrive_tpu.devsched import (
    DeficitRoundRobin,
    DeviceFuture,
    DeviceWorkQueue,
    FifoDrainPolicy,
    NullVerifyLauncher,
    QueueFlusher,
    SpeculationMismatch,
    VerifyLauncher,
)
from hyperdrive_tpu.harness.sim import Simulation
from hyperdrive_tpu.verifier import HostVerifier, NullVerifier

# ------------------------------------------------------- queue mechanics


class CountingLauncher:
    """Echo launcher: each payload's result is the payload itself;
    records every launch's shape for coalescing assertions."""

    kind = "echo"

    def __init__(self):
        self.launches = []

    def launch(self, payloads):
        self.launches.append([len(p) for p in payloads])
        return [list(p) for p in payloads]


def test_submit_returns_pending_future_and_drain_resolves():
    q = DeviceWorkQueue()
    launcher = CountingLauncher()
    fut = q.submit(launcher, [1, 2, 3])
    assert not fut.done() and q.depth == 1
    assert q.drain() == 1
    assert fut.done() and fut.result() == [1, 2, 3]
    assert launcher.launches == [[3]]


def test_per_submitter_fifo_resolution_order():
    # Futures resolve in global submission order — per-submitter FIFO
    # is a corollary. Interleave three "replicas"; the callback log
    # must replay the exact submission sequence.
    q = DeviceWorkQueue()
    launcher = CountingLauncher()
    order = []
    expect = []
    for step in range(9):
        replica = step % 3
        tag = (replica, step)
        expect.append(tag)
        fut = q.submit(launcher, [tag])
        fut.add_done_callback(lambda f, t=tag: order.append(t))
    q.drain()
    assert order == expect
    # ...and they all rode ONE launch.
    assert launcher.launches == [[1] * 9]


def test_coalescing_groups_by_launcher_in_first_submission_order():
    q = DeviceWorkQueue()
    a, b = CountingLauncher(), CountingLauncher()
    q.submit(a, [1])
    q.submit(b, [2, 2])
    q.submit(a, [3])
    assert q.drain() == 3
    assert a.launches == [[1, 1]]  # two commands, one launch
    assert b.launches == [[2]]
    assert q.launches == 2 and q.coalesced == 1


def test_future_fanout_multiple_callbacks_in_order():
    q = DeviceWorkQueue()
    fut = q.submit(CountingLauncher(), [7])
    seen = []
    fut.add_done_callback(lambda f: seen.append(("first", f.result())))
    fut.add_done_callback(lambda f: seen.append(("second", f.result())))
    q.drain()
    assert seen == [("first", [7]), ("second", [7])]
    # Late registration fires immediately on a resolved future.
    fut.add_done_callback(lambda f: seen.append(("late", f.result())))
    assert seen[-1] == ("late", [7])


def test_callbacks_may_submit_more_work_into_same_drain():
    q = DeviceWorkQueue()
    launcher = CountingLauncher()
    results = []

    def chain(f):
        results.append(f.result())
        if len(results) < 3:
            q.submit(launcher, [len(results)]).add_done_callback(chain)

    q.submit(launcher, [0]).add_done_callback(chain)
    resolved = q.drain()
    assert resolved == 3
    assert results == [[0], [1], [2]]
    assert q.depth == 0


def test_max_depth_auto_drains_on_submit():
    q = DeviceWorkQueue(max_depth=2)
    launcher = CountingLauncher()
    f1 = q.submit(launcher, [1])
    assert not f1.done()
    f2 = q.submit(launcher, [2])  # hits the bound -> drains both
    assert f1.done() and f2.done()
    assert launcher.launches == [[1, 1]]


def test_cancel_skips_resolution_and_launch():
    q = DeviceWorkQueue()
    launcher = CountingLauncher()
    fut = q.submit(launcher, [1])
    live = q.submit(launcher, [2])
    assert fut.cancel()
    q.drain()
    assert fut.cancelled() and live.result() == [2]
    # The cancelled payload never reached the device.
    assert launcher.launches == [[1]]
    with pytest.raises(RuntimeError, match="cancelled"):
        fut.result()
    assert not live.cancel()  # already resolved


def test_result_forces_drain():
    q = DeviceWorkQueue()
    fut = q.submit(CountingLauncher(), [5])
    assert fut.result() == [5]  # blocking escape hatch drains the queue
    assert q.depth == 0


def test_close_drains_then_rejects_submits():
    # Drain-on-shutdown: nothing pending may be silently dropped.
    q = DeviceWorkQueue()
    launcher = CountingLauncher()
    futs = [q.submit(launcher, [i]) for i in range(4)]
    assert q.close() == 4
    assert all(f.done() for f in futs)
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(launcher, [9])


def test_on_drain_fires_with_resolved_count():
    q = DeviceWorkQueue()
    counts = []
    q.on_drain = counts.append
    q.submit(CountingLauncher(), [1])
    q.submit(CountingLauncher(), [2])
    q.drain()
    q.drain()  # empty drain must NOT fire the hook
    assert counts == [2]


def test_verify_launcher_memoized_per_verifier():
    q = DeviceWorkQueue()
    host, null = HostVerifier(), NullVerifier()
    assert q.verify_launcher(host) is q.verify_launcher(host)
    assert isinstance(q.verify_launcher(host), VerifyLauncher)
    # NullVerifier has no verify_signatures -> transport-trusting leg.
    assert isinstance(q.verify_launcher(null), NullVerifyLauncher)


def test_null_launcher_matches_null_verifier_verdicts():
    # Swapping NullVerifier flushing from blocking to queued must not
    # change verdicts: unsigned rows stay accepted.
    payload = [(b"\x00" * 32, b"\x01" * 32, None)] * 3
    assert NullVerifyLauncher().launch([payload]) == [[True, True, True]]


# ------------------------------------------------- tenant drain policies


def _submit_tenants(q, launcher, plan):
    """plan: list of (origin, rows) — submit one command each, payload
    is `rows` copies of the origin tag so results identify tenants."""
    return [
        q.submit(launcher, [origin] * rows, origin=origin, rows=rows)
        for origin, rows in plan
    ]


def test_fifo_policy_is_scheduling_identical_to_no_policy():
    plan = [("a", 3), ("b", 1), ("a", 2), ("c", 5), ("b", 4)]
    shapes = []
    for policy in (None, FifoDrainPolicy()):
        q = DeviceWorkQueue(policy=policy)
        launcher = CountingLauncher()
        futs = _submit_tenants(q, launcher, plan)
        q.drain()
        shapes.append(launcher.launches)
        assert [f.result() for f in futs] == [
            [o] * r for o, r in plan
        ]
    assert shapes[0] == shapes[1] == [[3, 1, 2, 5, 4]]


def test_drr_bounds_rows_per_cycle_and_shares_seats():
    # A firehose tenant (40 rows) next to two small tenants: the DRR
    # capacity splits one monster launch into a bounded train, and the
    # small tenants ride the FIRST launch instead of queuing behind
    # the firehose.
    q = DeviceWorkQueue(
        policy=DeficitRoundRobin(capacity_rows=16, quantum_rows=8)
    )
    launcher = CountingLauncher()
    plan = [("fire", 10)] * 4 + [("b", 2), ("c", 2)]
    futs = _submit_tenants(q, launcher, plan)
    q.drain()
    assert all(f.done() for f in futs)  # nothing leaks past a drain
    assert len(launcher.launches) > 1  # the train, not one monster
    assert all(sum(shape) <= 16 for shape in launcher.launches)
    first = launcher.launches[0]
    # Small tenants seated in cycle 1 alongside ONE firehose window.
    assert 2 in first and first.count(10) <= 2


def test_drr_starvation_bound_forces_selection():
    # quantum 1 << rows 8 means tenant "slow" can never afford its
    # command through deficit alone before the bound fires; after
    # starve_after deferrals it MUST be force-selected.
    policy = DeficitRoundRobin(
        capacity_rows=8, quantum_rows=1, starve_after=3
    )
    q = DeviceWorkQueue(policy=policy)
    launcher = CountingLauncher()
    slow = q.submit(launcher, ["s"] * 8, origin="slow", rows=8)
    # Competing 1-row traffic resubmitted by callbacks keeps cycles
    # coming without ever letting "slow"'s deficit catch up cheaply.
    count = [0]

    def resubmit(f):
        count[0] += 1
        if count[0] < 12:
            q.submit(
                launcher, ["t"], origin="talk", rows=1
            ).add_done_callback(resubmit)

    q.submit(launcher, ["t"], origin="talk", rows=1).add_done_callback(
        resubmit
    )
    q.drain()
    assert slow.done() and slow.result() == ["s"] * 8
    assert policy.forced_total >= 1
    # The spec'd fairness bound: nothing ever waits more cycles than
    # starve_after (the chaos invariant).
    assert policy.max_deferrals <= policy.starve_after


def test_drr_progress_guarantee_over_capacity_command():
    # A command larger than capacity_rows launches alone rather than
    # deadlocking the drain.
    q = DeviceWorkQueue(policy=DeficitRoundRobin(capacity_rows=4))
    launcher = CountingLauncher()
    fut = q.submit(launcher, ["x"] * 9, origin="big", rows=9)
    q.drain()
    assert fut.result() == ["x"] * 9
    assert launcher.launches == [[9]]


def test_drr_weights_tilt_occupancy():
    # Weight 3 vs 1 at equal demand: the heavy tenant gets more rows
    # into the first bounded cycle (credit 6/visit vs 2/visit).
    policy = DeficitRoundRobin(
        capacity_rows=8, quantum_rows=2, weights={"heavy": 3}
    )
    q = DeviceWorkQueue(policy=policy)

    class TaggingLauncher(CountingLauncher):
        def __init__(self):
            super().__init__()
            self.tags = []

        def launch(self, payloads):
            self.tags.append([p[0] for p in payloads])
            return super().launch(payloads)

    launcher = TaggingLauncher()
    plan = [("heavy", 2)] * 4 + [("light", 2)] * 4
    futs = _submit_tenants(q, launcher, plan)
    q.drain()
    assert all(f.done() for f in futs)
    assert all(sum(shape) <= 8 for shape in launcher.launches)
    first = launcher.tags[0]
    assert first.count("heavy") > first.count("light") >= 1


def test_drr_preserves_per_tenant_fifo():
    q = DeviceWorkQueue(
        policy=DeficitRoundRobin(capacity_rows=4, quantum_rows=4)
    )
    launcher = CountingLauncher()
    order = []
    for i in range(6):
        fut = q.submit(launcher, [("a", i)], origin="a", rows=1)
        fut.add_done_callback(lambda f, i=i: order.append(i))
    q.drain()
    assert order == sorted(order)


def test_policy_validation():
    with pytest.raises(ValueError, match="capacity_rows"):
        DeficitRoundRobin(capacity_rows=0)
    with pytest.raises(ValueError, match="quantum_rows"):
        DeficitRoundRobin(quantum_rows=0)
    with pytest.raises(ValueError, match="starve_after"):
        DeficitRoundRobin(starve_after=0)


# ------------------------------------------------ sim integration (burst)

_SIGNED = dict(
    n=4, target_height=6, seed=7, sign=True, burst=True, observe=True
)


def test_pipelined_digest_parity_with_sequential():
    seq = Simulation(**_SIGNED)
    res_seq = seq.run()
    pipe = Simulation(pipeline_heights=True, **_SIGNED)
    res_pipe = pipe.run()
    assert res_seq.completed and res_pipe.completed
    assert res_seq.commit_digest() == res_pipe.commit_digest()
    # Pipelining actually engaged: settles coalesced across heights.
    assert pipe._sched.coalesced > 0
    assert pipe._sched.launches < pipe._sched.submitted
    assert pipe._sched.depth == 0  # drained before the result returned


def test_pipelined_run_is_deterministic_at_fixed_seed():
    # Same seed, same config -> identical coalescing decisions,
    # identical obs journal, identical chain.
    a = Simulation(pipeline_heights=True, **_SIGNED)
    res_a = a.run()
    b = Simulation(pipeline_heights=True, **_SIGNED)
    res_b = b.run()
    assert res_a.commit_digest() == res_b.commit_digest()
    assert a.obs.digest() == b.obs.digest()
    assert (a._sched.submitted, a._sched.launches, a._sched.coalesced) == (
        b._sched.submitted, b._sched.launches, b._sched.coalesced
    )


def test_forged_signature_raises_speculation_mismatch():
    # Speculation accepts parseable-and-signed rows; a verifier that
    # rejects them all at drain time means forged-but-well-formed
    # signatures — the pipeline must fail loudly, not diverge.
    sim = Simulation(pipeline_heights=True, **_SIGNED)
    launcher = sim._sched.verify_launcher(sim.batch_verifier)
    launcher.verifier = type(
        "Forged", (), {
            "verify_signatures": staticmethod(
                lambda items: [False] * len(items)
            )
        }
    )()
    with pytest.raises(SpeculationMismatch):
        sim.run()


def test_pipeline_heights_requires_burst():
    with pytest.raises(ValueError, match="burst"):
        Simulation(n=4, target_height=3, sign=True, pipeline_heights=True)


def test_pipeline_heights_requires_a_verifier():
    with pytest.raises(ValueError, match="batch_verifier"):
        Simulation(
            n=4, target_height=3, burst=True, pipeline_heights=True
        )


def test_flusher_for_rejects_burst_mode():
    q = DeviceWorkQueue()
    with pytest.raises(ValueError, match="lock-step"):
        Simulation(
            n=4, target_height=3, burst=True, sign=True,
            devsched=q,
            flusher_for=lambda i, v: QueueFlusher(NullVerifier(), q),
        )


# -------------------------------------------- lock-step flusher pipeline


def test_lockstep_queue_flusher_digest_parity():
    # The chaos-soak leg: unsigned lock-step replicas flushing through
    # one shared queue commit the same chain as plain sequential
    # delivery, with real cross-replica coalescing.
    kw = dict(
        n=4, target_height=8, seed=31, timeout=1.0,
        delivery_cost=1e-3, observe=True,
    )
    seq = Simulation(**kw)
    res_seq = seq.run()
    queue = DeviceWorkQueue(max_depth=8)
    pipe = Simulation(
        devsched=queue,
        flusher_for=lambda i, validators: QueueFlusher(
            NullVerifier(), queue
        ),
        **kw,
    )
    res_pipe = pipe.run()
    assert res_seq.commit_digest() == res_pipe.commit_digest()
    assert queue.coalesced > 0 and queue.depth == 0
    flushers = [r.flusher for r in pipe.replicas]
    assert sum(f.dispatched for f in flushers) == sum(
        f.submitted for f in flushers
    )


def test_queue_flusher_reset_cancels_inflight():
    queue = DeviceWorkQueue()
    flusher = QueueFlusher(NullVerifier(), queue)
    fut = queue.submit(queue.verify_launcher(flusher.verifier), [])
    flusher._inflight.append(fut)
    flusher.reset()
    assert fut.cancelled() and not flusher._inflight
    queue.drain()  # cancelled command must not resolve or launch
    assert not fut.done() or fut.cancelled()


# ------------------------------------------------- multi-tenant service


def test_shard_verify_service_coalesces_tenants():
    from hyperdrive_tpu.parallel.multihost import ShardVerifyService

    class CountingVerifier:
        def __init__(self):
            self.calls = []

        def verify_signatures(self, items):
            self.calls.append(len(items))
            return [True] * len(items)

    ver = CountingVerifier()
    svc = ShardVerifyService(ver, max_depth=0)
    rows = [(b"\x00" * 32, b"\x01" * 32, b"\x02" * 64)]
    futs = [svc.submit(f"shard-{t}", rows * (t + 1)) for t in range(3)]
    assert svc.queue.depth == 3
    svc.drain()
    # Three tenants, ONE device call covering all six rows.
    assert ver.calls == [6]
    assert [len(f.result()) for f in futs] == [1, 2, 3]
    assert svc.tenants == {"shard-0": 1, "shard-1": 1, "shard-2": 1}
    svc.close()
