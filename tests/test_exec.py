"""The execution layer: deterministic ledger, device-kernel parity,
stake-driven epochs.

Unit layer: the order-independent block-atomic apply semantics
(handcrafted blocks + permutation invariance), the host/device kind
constants, root-chain determinism across resync gaps, and the
``exec.apply`` launcher riding the shared device-work drain.

Integration layer: full Simulation runs with ``execution=
ExecutionConfig(...)`` — root-extended commit values, record/replay
determinism (ScenarioRecord v7 execution trailer), device-vs-host
digest equality, and the stake-driven election specs: the elected
committee genuinely differs from the static-stake counterfactual, the
grinding resistance of proportional election, and retired keys across
a stake-changing boundary.
"""

import hashlib

import pytest

from hyperdrive_tpu.chaos.monitor import InvariantMonitor
from hyperdrive_tpu.devsched.queue import DeviceWorkQueue
from hyperdrive_tpu.epochs import EpochConfig, elect_committee
from hyperdrive_tpu.exec import ExecutionConfig
from hyperdrive_tpu.exec.ledger import (
    KIND_STAKE,
    KIND_TRANSFER,
    KIND_UNSTAKE,
    BlockSource,
    ExecApplyLauncher,
    HostLedgerExecutor,
    TxBlock,
    pack_state,
)
from hyperdrive_tpu.harness.sim import ScenarioRecord, Simulation


def _cfg(**kw) -> ExecutionConfig:
    base = dict(
        accounts=32,
        txs_per_block=24,
        stake_every=3,
        stake_accounts=8,
        seed=9,
        amount_cap=16,
        initial_balance=500,
    )
    base.update(kw)
    if base["stake_accounts"] > base["accounts"]:
        base["stake_accounts"] = base["accounts"] // 2
    return ExecutionConfig(**base)


def _block(height, rows) -> TxBlock:
    kind = [r[0] for r in rows]
    sender = [r[1] for r in rows]
    recipient = [r[2] for r in rows]
    amount = [r[3] for r in rows]
    return TxBlock(
        height, kind, sender, recipient, amount,
        hashlib.sha256(repr(rows).encode()).digest(),
    )


def _apply_rows(executor, rows):
    return executor._apply_block(_block(1, rows), None)


# ----------------------------------------------------------------- semantics


def test_kind_constants_match_device_kernel():
    from hyperdrive_tpu.ops import ledger as ops_ledger

    assert KIND_TRANSFER == ops_ledger.KIND_TRANSFER
    assert KIND_STAKE == ops_ledger.KIND_STAKE
    assert KIND_UNSTAKE == ops_ledger.KIND_UNSTAKE


def test_block_atomic_insolvency_kills_every_tx_of_the_sender():
    # Sender 0 holds 10; two 6-unit transfers are each affordable alone
    # but not together — the block-atomic rule rejects BOTH (solvency is
    # a statement about the pre-block snapshot, not a running balance).
    ex = HostLedgerExecutor(_cfg(accounts=4, initial_balance=10))
    applied = _apply_rows(ex, [
        (KIND_TRANSFER, 0, 1, 6),
        (KIND_TRANSFER, 0, 2, 6),
        (KIND_TRANSFER, 3, 1, 6),   # a solvent bystander still lands
    ])
    assert applied == 1
    assert ex.balances[0] == 10 and ex.balances[3] == 4
    assert ex.balances[1] == 16 and ex.balances[2] == 10
    # Alone, the same transfer goes through.
    ex2 = HostLedgerExecutor(_cfg(accounts=4, initial_balance=10))
    assert _apply_rows(ex2, [(KIND_TRANSFER, 0, 1, 6)]) == 1
    assert ex2.balances[0] == 4


def test_stake_and_unstake_move_between_columns():
    ex = HostLedgerExecutor(
        _cfg(accounts=4, initial_balance=10), genesis_stakes=(0, 7)
    )
    applied = _apply_rows(ex, [
        (KIND_STAKE, 0, 0, 4),
        (KIND_UNSTAKE, 1, 1, 5),
        (KIND_UNSTAKE, 2, 2, 1),    # no stake to unstake: rejected
    ])
    assert applied == 2
    assert (ex.balances[0], ex.stakes[0]) == (6, 4)
    assert (ex.balances[1], ex.stakes[1]) == (15, 2)
    assert (ex.balances[2], ex.stakes[2]) == (10, 0)
    assert ex.rejected_total == 0  # _apply_block alone doesn't count


def test_apply_is_order_independent():
    import random

    rows = []
    rnd = random.Random(3)
    for _ in range(40):
        rows.append((
            rnd.choice((KIND_TRANSFER, KIND_STAKE, KIND_UNSTAKE)),
            rnd.randrange(8), rnd.randrange(8), rnd.randint(1, 20),
        ))
    ref = HostLedgerExecutor(_cfg(accounts=8, initial_balance=30))
    n_ref = _apply_rows(ref, rows)
    for i in range(4):
        shuffled = rows[:]
        random.Random(i).shuffle(shuffled)
        ex = HostLedgerExecutor(_cfg(accounts=8, initial_balance=30))
        assert _apply_rows(ex, shuffled) == n_ref
        assert ex.balances == ref.balances and ex.stakes == ref.stakes


def test_root_chain_deterministic_across_resync_gaps():
    cfg = _cfg()
    stepper = HostLedgerExecutor(cfg)
    for h in range(1, 6):
        stepper.advance_to(h)
    jumper = HostLedgerExecutor(cfg)
    assert jumper.advance_to(5) == stepper.roots[5]
    assert jumper.roots == stepper.roots
    # Re-asking a settled height is a cached read, not a re-apply.
    assert jumper.advance_to(3) == stepper.roots[3]
    assert jumper.height == 5
    # Genesis root is a pure function of the config.
    assert jumper.genesis_root == stepper.genesis_root
    assert jumper.advance_to(0) == jumper.genesis_root


def test_pack_state_is_le64_signed():
    assert pack_state([1, -2]) == (1).to_bytes(8, "little", signed=True) + (
        -2
    ).to_bytes(8, "little", signed=True)


def test_execution_config_rejects_overflow_risk():
    with pytest.raises(ValueError):
        ExecutionConfig(
            accounts=8, txs_per_block=2**20, amount_cap=2**12,
            initial_balance=2**30,
        )
    cfg = _cfg()
    assert ExecutionConfig.from_ints(cfg.as_ints()) == cfg


# -------------------------------------------------------------- device parity


def test_device_executor_matches_host_reference():
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor

    for seed in (1, 2, 3):
        cfg = _cfg(seed=seed, txs_per_block=64, initial_balance=40)
        src = BlockSource(cfg)
        host = HostLedgerExecutor(cfg, source=src, genesis_stakes=(5, 5))
        dev = DeviceLedgerExecutor(cfg, source=src, genesis_stakes=(5, 5))
        assert dev.genesis_root == host.genesis_root
        host.advance_to(4)
        dev.advance_to(4)
        assert dev.roots == host.roots
        assert dev.applied_total == host.applied_total
        assert dev.rejected_total == host.rejected_total
        assert list(dev.balances) == list(host.balances)
        assert list(dev.stakes) == list(host.stakes)


def test_device_executor_matches_host_on_signed_blocks():
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor

    cfg = _cfg(sign_txs=True, bad_sig_every=5, txs_per_block=16)
    src = BlockSource(cfg)
    host = HostLedgerExecutor(cfg, source=src)
    dev = DeviceLedgerExecutor(cfg, source=src)
    host.advance_to(2)
    dev.advance_to(2)
    assert dev.roots == host.roots
    # Every 5th lane was corrupted: the mask must have rejected them.
    assert host.rejected_total >= 2 * (16 // 5)


# ------------------------------------------------------------------ launcher


def test_exec_apply_launcher_rides_the_shared_drain():
    from hyperdrive_tpu.verifier import HostVerifier

    cfg = _cfg(sign_txs=True, bad_sig_every=4, txs_per_block=12)
    src = BlockSource(cfg)
    blk = src.block(1)
    items = src.sig_items(blk)

    q = DeviceWorkQueue()
    verifier = HostVerifier()
    exec_launcher = ExecApplyLauncher(verifier)
    assert exec_launcher.kind == "exec.apply"
    vote_launcher = q.verify_launcher(verifier)
    f_vote = q.submit(vote_launcher, items[:2])
    f_exec = q.submit(exec_launcher, items)
    assert not f_exec.done()
    # ONE drain cycle resolves both command kinds (grouped by launcher
    # identity, so the exec launch coalesces separately from votes).
    q.drain()
    assert f_vote.done() and f_exec.done()
    mask = f_exec.result()
    assert len(mask) == len(blk)
    want = [bool(v) for v in verifier.verify_signatures(items)]
    assert mask == want
    assert not all(mask)  # the corrupted lanes really got rejected
    assert q.launches >= 2  # distinct launchers never share a launch

    # The mask is exactly what a maskless executor derives host-side:
    # launcher path and fallback path are digest-identical.
    with_mask = HostLedgerExecutor(cfg, source=src, masks={1: mask})
    without = HostLedgerExecutor(cfg, source=src)
    assert with_mask.advance_to(1) == without.advance_to(1)


# ----------------------------------------------------------------- harness


def _exec_sim(seed=13, device=False, target=6, **kw) -> Simulation:
    cfg = _cfg(seed=seed, device=device, txs_per_block=12)
    return Simulation(
        n=4, target_height=target, seed=seed, execution=cfg, **kw
    )


def test_sim_commits_are_root_extended_and_replayable(tmp_path):
    sim = _exec_sim(observe=True)
    res = sim.run()
    assert res.completed
    ref = HostLedgerExecutor(_cfg(seed=13, txs_per_block=12))
    for i in range(sim.n):
        for h, value in sim.commits[i].items():
            assert len(value) == 64  # 32-byte value + 32-byte root
            assert value[32:] == ref.advance_to(h)
    assert sum(e.applied_total for e in sim.executors) > 0
    # Record/replay: the v7 execution trailer reproduces the identical
    # root-extended chain from the config ints alone.
    path = str(tmp_path / "exec.bin")
    sim.record.dump(path)
    rec = ScenarioRecord.load(path)
    assert rec.execution == _cfg(seed=13, txs_per_block=12).as_ints()
    replayed = Simulation.replay(rec)
    assert replayed.completed
    assert replayed.commits == res.commits


def test_sim_device_executor_is_digest_identical_to_host():
    host = _exec_sim(seed=21, device=False).run()
    dev = _exec_sim(seed=21, device=True).run()
    assert host.completed and dev.completed
    assert dev.commits == host.commits


# ------------------------------------------------------- stake-driven epochs


def _stake_sim(seed=17, target=9, **kw) -> Simulation:
    # Heavy stake churn: every other tx is a STAKE/UNSTAKE on one of
    # the n validator accounts, so the ledger's stake column drifts
    # hard between boundaries.
    cfg = _cfg(
        seed=seed, accounts=16, txs_per_block=32, stake_every=2,
        stake_accounts=4, amount_cap=32, initial_balance=2000,
    )
    return Simulation(
        n=4,
        target_height=target,
        seed=seed,
        execution=cfg,
        epochs=EpochConfig(epoch_length=3, committee_size=3),
        certificates=True,
        **kw,
    )


def test_elections_read_stake_from_committed_state():
    sim = _stake_sim()
    res = sim.run()
    assert res.completed and sim.epoch >= 2
    sched = sim.epoch_schedule
    # The sim seeds the ledger's stake column with the epoch pool's
    # genesis stakes (uniform 1 when EpochConfig.stakes is ()), so the
    # reference executor must start from the same genesis.
    ref = HostLedgerExecutor(
        _cfg(
            seed=17, accounts=16, txs_per_block=32, stake_every=2,
            stake_accounts=4, amount_cap=32, initial_balance=2000,
        ),
        genesis_stakes=sched.stakes,
    )
    differs = 0
    for e in range(1, sim.epoch + 1):
        tr = sched.transition(e)
        boundary = sched.boundary_height(e - 1)
        # The committee the sim elected == the committee elected from
        # the ledger's floored stake column at the boundary height.
        ref.advance_to(boundary)
        stakes = ref.election_stakes(sim.n)
        want = elect_committee(
            stakes, sched.committee_size, sched.anchor(e) + b"elect"
        )
        assert tuple(v.index for v in tr.committee) == want
        assert tuple(v.stake for v in tr.committee) == tuple(
            stakes[i] for i in want
        )
        # The acceptance counterfactual: a static-stake election at the
        # same anchor seats a DIFFERENT committee — the stake the
        # ledger accumulated genuinely drove the outcome.
        static = elect_committee(
            sched.stakes, sched.committee_size, sched.anchor(e) + b"elect"
        )
        if want != static:
            differs += 1
    assert differs > 0, (
        "every elected committee matched the static-stake counterfactual "
        "— elections are not reading committed state"
    )


def test_stake_floor_keeps_drained_validators_electable():
    cfg = _cfg(stake_floor=7)
    ex = HostLedgerExecutor(cfg)  # zero genesis stake everywhere
    stakes = ex.election_stakes(4)
    assert stakes == (7, 7, 7, 7)
    # A floored pool is always electable even when the ledger has
    # drained every validator account to zero.
    assert len(elect_committee(stakes, 3, b"m")) == 3


def test_grinding_by_stake_splitting_buys_no_extra_seats():
    # Proportional election's grinding resistance: an adversary
    # splitting one 40-unit stake across two sybil accounts wins seats
    # at the same aggregate rate as the merged whale. 256 independent
    # anchors, 3-of-N committees; the split pool has one more member.
    merged = (40,) + (10,) * 6
    split = (20, 20) + (10,) * 6
    rounds = 256
    merged_wins = sum(
        0 in elect_committee(merged, 3, b"grind%d" % i)
        for i in range(rounds)
    )
    split_wins = sum(
        bool({0, 1} & set(elect_committee(split, 3, b"grind%d" % i)))
        for i in range(rounds)
    )
    assert abs(merged_wins - split_wins) <= rounds * 0.12, (
        f"splitting moved the whale's seat rate from "
        f"{merged_wins}/{rounds} to {split_wins}/{rounds}"
    )


def test_rekey_across_stake_changing_boundary():
    # Key rotation and stake-driven election compose: a committee
    # member retires its identity at a boundary whose election read
    # freshly-mutated stake, and the run stays fork-free with the
    # monitor's exec invariants armed (root agreement + commit/ledger
    # binding).
    sim = _stake_sim(seed=23, target=9, observe=True)
    mon = InvariantMonitor(sim)
    res = sim.run()
    mon.check_final(res)
    assert res.completed and sim.epoch >= 2
    assert sim._retired, "no key was ever rotated out"
    assert len(mon.epoch_switches) >= 2
    retired_epochs = [
        e for e in range(1, sim.epoch + 1)
        if sim.epoch_schedule.transition(e).rekeyed
    ]
    assert retired_epochs, "no transition rotated a key"


# ------------------------------------------------------ speculative pipeline


def test_speculation_mismatch_rolls_back_bit_identically():
    # A wrong admission guess must unwind state, root, and counters to
    # EXACTLY what a never-speculated executor derives — and the roots
    # computed under the wrong guess must land in discarded_roots,
    # disjoint from the settled chain.
    from hyperdrive_tpu.verifier import HostVerifier

    cfg = _cfg(sign_txs=True, bad_sig_every=4, txs_per_block=12)
    src = BlockSource(cfg)
    ref = HostLedgerExecutor(cfg, source=src)
    ref.advance_to(3)

    ex = HostLedgerExecutor(cfg, source=src)
    guess = [True] * cfg.txs_per_block  # forged lanes look well-formed
    for h in (1, 2, 3):
        ex.speculate(h, list(guess))
    verifier = HostVerifier()
    for h in (1, 2, 3):
        mask = [
            bool(v)
            for v in verifier.verify_signatures(src.sig_items(src.block(h)))
        ]
        ex.resolve(h, mask)
    assert ex.spec_rolled_back >= 1
    assert not ex._spec  # every window settled
    assert ex.balances == ref.balances
    assert ex.stakes == ref.stakes
    assert ex.root == ref.root
    assert ex.roots == ref.roots
    assert ex.applied_total == ref.applied_total
    assert ex.rejected_total == ref.rejected_total
    assert ex.discarded_roots
    assert not ex.discarded_roots & set(ex.roots.values())
    ex.host_verify()


def test_speculation_confirm_path_and_ordering_guards():
    cfg = _cfg(txs_per_block=12)
    src = BlockSource(cfg)
    ex = HostLedgerExecutor(cfg, source=src)
    ex.speculate(1, None)
    with pytest.raises(ValueError):
        ex.speculate(3, None)  # strictly upward
    ex.speculate(2, None)
    # advance_to confirms exact windows in passing (the commit seam).
    ref = HostLedgerExecutor(cfg, source=src)
    assert ex.advance_to(2) == ref.advance_to(2)
    assert ex.spec_confirmed == 2 and ex.spec_rolled_back == 0
    # A signed guess cannot be confirmed blind: commits must wait for
    # the verify verdict.
    sx = HostLedgerExecutor(
        _cfg(sign_txs=True, txs_per_block=12),
        source=BlockSource(_cfg(sign_txs=True, txs_per_block=12)),
    )
    sx.speculate(1, [True] * 12)
    with pytest.raises(RuntimeError):
        sx.confirm_to(1)


def test_fused_drain_matches_two_kind_drain_and_saves_launches():
    # The fused drain coalesces exec signature rows into the SAME
    # launch as the vote verifies; the two-kind path gives exec rows
    # their own launch per drain. Same chain either way, fewer
    # launches fused.
    cfg = _cfg(seed=11, sign_txs=True, txs_per_block=12)
    kw = dict(
        n=4, target_height=5, seed=11, sign=True, burst=True,
        pipeline_heights=True, execution=cfg,
    )
    fused = Simulation(fused_exec_drain=True, **kw)
    rf = fused.run()
    two = Simulation(fused_exec_drain=False, **kw)
    rt = two.run()
    assert rf.commits == rt.commits
    assert fused._sched.launches < two._sched.launches


def test_pipelined_matches_sequential_under_drr_drain_policy():
    # Deferrals from a row-capped DeficitRoundRobin reorder WHEN exec
    # rows verify, never what the chain settles to: the pipelined run
    # must agree with the plain sequential settle-then-execute run on
    # every common height.
    from hyperdrive_tpu.devsched import DeficitRoundRobin, DeviceWorkQueue

    cfg = _cfg(seed=19, sign_txs=True, txs_per_block=12)
    queue = DeviceWorkQueue(
        max_depth=4,
        policy=DeficitRoundRobin(
            capacity_rows=32, quantum_rows=8, starve_after=3
        ),
    )
    pip = Simulation(
        n=4, target_height=5, seed=19, sign=True, burst=True,
        pipeline_heights=True, devsched=queue, execution=cfg,
    )
    rp = pip.run()
    seq = Simulation(
        n=4, target_height=5, seed=19, sign=True, burst=True,
        pipeline_heights=False, execution=cfg,
    )
    rs = seq.run()
    for i in range(4):
        common = set(rp.commits[i]) & set(rs.commits[i])
        assert common
        for h in common:
            assert rp.commits[i][h] == rs.commits[i][h]


def test_block_source_cache_pins_open_epoch_and_counts():
    # The LRU never evicts an entry touched in the OPEN speculation
    # epoch (a rollback may replay it); closing the window (epoch
    # bump) releases the pins. hits/misses/evictions make the policy
    # observable.
    cfg = _cfg(txs_per_block=8)
    src = BlockSource(cfg)
    cap = BlockSource.CACHE
    for h in range(1, cap + 3):
        src.block(h)
    # Every entry belongs to the open epoch: pinned, so the cache grew
    # past capacity rather than evicting.
    assert src.misses == cap + 2
    assert src.evictions == 0
    assert len(src._cache) == cap + 2
    src.block(1)
    assert src.hits == 1  # still resident
    # Close the window: the next insert may evict the stale epoch.
    src.spec_epoch += 1
    src.block(cap + 3)
    assert src.evictions > 0
    assert len(src._cache) <= cap
    # An entry re-touched in the new epoch is pinned again.
    src.block(cap + 3)
    assert src.hits == 2


def test_prove_verifies_on_both_executors_and_after_rollback():
    # The Merkle surface end to end: host and device executors serve
    # bit-identical proofs, every proof verifies against the chained
    # root a light client already trusts, and a speculation rollback
    # restores the dirty-set snapshot so post-rollback proofs verify
    # against the rebuilt chain.
    from hyperdrive_tpu.exec.device import DeviceLedgerExecutor

    cfg = _cfg(seed=21, txs_per_block=16)
    src = BlockSource(cfg)
    host = HostLedgerExecutor(cfg, source=src)
    dev = DeviceLedgerExecutor(cfg, source=src)
    for ex in (host, dev):
        ex.advance_to(4)
    for account in (0, 9, 31):
        hp, dp = host.prove(account), dev.prove(account)
        assert hp == dp
        assert host.verify_inclusion(
            host.roots[4], account, hp.balance, hp.stake, hp
        )
    # Roll a speculative window back; the tree snapshot restores with
    # the state, and a fresh proof verifies against the replayed chain.
    for ex in (host, dev):
        ex.speculate(5, [i % 2 == 0 for i in range(cfg.txs_per_block)])
        with pytest.raises(RuntimeError):
            ex.prove(3)  # speculative roots may roll back: refuse
        ex.resolve(5, [True] * cfg.txs_per_block)
        assert ex.spec_rolled_back == 1
        p = ex.prove(3)
        assert ex.verify_inclusion(
            ex.roots[5], 3, p.balance, p.stake, p
        )
    assert host.root == dev.root
    assert host.prove(3) == dev.prove(3)


def test_proof_basis_is_frozen_against_executor_progress():
    cfg = _cfg(seed=25)
    ex = HostLedgerExecutor(cfg)
    ex.advance_to(2)
    basis = ex.proof_basis()
    frozen = basis.prove(4)
    root_h2 = ex.roots[2]
    # The executor moves on (and even speculates); the basis still
    # serves height-2 proofs that verify against the height-2 root.
    ex.advance_to(5)
    ex.speculate(6, None)
    again = basis.prove(4)
    assert again == frozen and again.height == 2
    assert ex.verify_inclusion(
        root_h2, 4, frozen.balance, frozen.stake, frozen
    )
    with pytest.raises(RuntimeError):
        ex.proof_basis()  # open speculation window refuses


def test_merkle_events_ride_the_journal_on_both_routes():
    from hyperdrive_tpu.obs.report import proofs_summary

    for device in (False, True):
        sim = _exec_sim(device=device, target=3, observe=True)
        sim.run()
        summary = proofs_summary(sim.obs.snapshot())
        assert summary["updates"] >= 3
        assert summary["merkle_roots"]
        assert summary["merkle_forks"] == []
        assert summary["depth"] == 5  # 32 accounts
        assert summary["full_rebuilds"] in (0, summary["updates"])


def test_exec_report_renders_speculation_outcome_table():
    from hyperdrive_tpu.obs.report import exec_summary, render_exec_table

    cfg = _cfg(seed=29, txs_per_block=12)
    sim = Simulation(
        n=4, target_height=4, seed=29, sign=True, burst=True,
        pipeline_heights=True, execution=cfg, observe=True,
    )
    sim.run()
    summary = exec_summary(sim.obs.snapshot())
    spec = summary["spec_per_replica"]
    assert spec, "pipelined run journalled no speculation events"
    totals = {k: sum(s[k] for s in spec.values()) for k in
              ("speculated", "confirmed", "rolled_back")}
    assert totals["speculated"] >= 4
    assert totals["confirmed"] + totals["rolled_back"] == totals["speculated"]
    ex = sim._exec_unique[0]
    assert totals["confirmed"] == ex.spec_confirmed
    assert totals["rolled_back"] == ex.spec_rolled_back
    text = render_exec_table(summary)
    assert "speculation outcomes:" in text
    assert "rolled back" in text
