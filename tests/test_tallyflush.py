"""DeviceTallyFlusher: the deployment (n=1) vote-grid flush behind a
replica's own event loop (hyperdrive_tpu/tallyflush.py).

The sim certifies aggregated multi-replica settles; these tests certify
the per-replica composition a deployment runs: drain -> verify -> insert
(+ grid scatter) -> ONE tally launch -> cascade on device counts, with
every device-sourced count cross-checked against the host counters.
Reference integration shape: /root/reference/replica/replica_test.go.
"""

import hashlib

import pytest

from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.ops.votegrid import CheckedTallyView
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.tallyflush import DeviceTallyFlusher
from hyperdrive_tpu.testutil import (
    CommitterCallback,
    MockProposer,
    MockValidator,
)
from hyperdrive_tpu.types import INVALID_ROUND
from hyperdrive_tpu.verifier import NullVerifier

N = 4
SIGS = [bytes([i + 1]) * 32 for i in range(N)]


def _value(height, round_):
    return hashlib.sha256(b"flushval-%d-%d" % (height, round_)).digest()


class _Loopback:
    """Broadcaster wired straight back into the replica — the Broadcaster
    contract includes self-delivery, and handle()'s reentrancy buffer
    serializes it (the moral inbox hop)."""

    def __init__(self):
        self.rep = None

    def broadcast_propose(self, m):
        self.rep.handle(m)

    broadcast_prevote = broadcast_precommit = broadcast_propose


def _build(flusher=None, commits=None):
    lb = _Loopback()
    rep = Replica(
        ReplicaOptions(),
        whoami=SIGS[0],
        signatories=list(SIGS),
        timer=None,
        proposer=MockProposer(fn=_value),
        validator=MockValidator(ok=True),
        committer=CommitterCallback(
            on_commit=lambda h, v: (commits.__setitem__(h, v), (0, None))[1]
        ),
        catcher=None,
        broadcaster=lb,
        verifier=NullVerifier() if flusher is None else None,
        flusher=flusher,
    )
    lb.rep = rep
    return rep


def _script(heights):
    """The other three validators' messages for a clean run of
    ``heights`` heights, round 0 each: proposer is (h+0) % N, replica 0's
    own votes self-deliver via the loopback."""
    msgs = []
    for h in range(1, heights + 1):
        proposer = SIGS[h % N]
        v = _value(h, 0)
        if proposer != SIGS[0]:
            msgs.append(Propose(height=h, round=0,
                                valid_round=INVALID_ROUND, value=v,
                                sender=proposer))
        for s in SIGS[1:]:
            msgs.append(Prevote(height=h, round=0, value=v, sender=s))
        for s in SIGS[1:]:
            msgs.append(Precommit(height=h, round=0, value=v, sender=s))
    return msgs


def test_flusher_drives_commits_counts_checked():
    """Three heights through the flusher seam: device tally counts are
    consulted (hits > 0), every one equals the host counters
    (CheckedTallyView raises otherwise), and the committed chain equals a
    plain host replica fed the identical script."""
    views = []

    def check(view, proc):
        cv = CheckedTallyView(view, proc)
        views.append(cv)
        return cv

    commits_dev: dict = {}
    fl = DeviceTallyFlusher(NullVerifier(), SIGS, tally_check=check)
    fl.warmup()
    rep_dev = _build(flusher=fl, commits=commits_dev)
    commits_host: dict = {}
    rep_host = _build(commits=commits_host)

    rep_dev.start()
    rep_host.start()
    for m in _script(3):
        rep_dev.handle(m)
        rep_host.handle(m)

    assert set(commits_dev) == {1, 2, 3}
    assert commits_dev == commits_host
    assert commits_dev[2] == _value(2, 0)
    assert fl.launches > 0
    assert sum(v.hits for v in views) > 0


def test_flusher_resets_grid_across_heights():
    """The grid plane resets when the height moves: votes for height 2
    tally from a clean plane (stale height-1 rows would otherwise
    inflate counts — CheckedTallyView would catch the divergence)."""
    commits: dict = {}
    fl = DeviceTallyFlusher(
        NullVerifier(), SIGS,
        tally_check=lambda view, proc: CheckedTallyView(view, proc),
    )
    rep = _build(flusher=fl, commits=commits)
    rep.start()
    for m in _script(2):
        rep.handle(m)
    assert set(commits) == {1, 2}


def test_flusher_rejected_votes_never_reach_grid():
    """A verifier rejecting one sender's votes: the automaton never sees
    them, the grid never scatters them, quorum still reached via the
    other 2f+1 — and counts still host-equal."""

    class _RejectOne:
        def verify_batch(self, window):
            return [m.sender != SIGS[3] for m in window]

    commits: dict = {}
    fl = DeviceTallyFlusher(
        _RejectOne(), SIGS,
        tally_check=lambda view, proc: CheckedTallyView(view, proc),
    )
    rep = _build(flusher=fl, commits=commits)
    rep.start()
    for m in _script(2):
        rep.handle(m)
    assert set(commits) == {1, 2}
    # The rejected sender's votes are absent from the host logs too.
    assert SIGS[3] not in rep.proc.state.prevote_logs.get(0, {})


def test_flusher_unknown_sender_poisons_round():
    """A whitelisted sender missing from the grid's validator axis
    (post-rotation shape): its rounds go dirty, the view declines them,
    the host counters stay authoritative, consensus still commits."""
    stranger = bytes([9]) * 32
    commits: dict = {}
    fl = DeviceTallyFlusher(
        NullVerifier(), SIGS,
        tally_check=lambda view, proc: CheckedTallyView(view, proc),
    )
    rep = _build(flusher=fl, commits=commits)
    rep.procs_allowed.add(stranger)
    rep.start()
    v = _value(1, 0)
    rep.handle(Prevote(height=1, round=0, value=v, sender=stranger))
    assert (0, 0) in fl._dirty
    for m in _script(1):
        rep.handle(m)
    assert set(commits) == {1}


@pytest.mark.parametrize("heights", [2])
def test_coalesced_threaded_drive_matches_sync(heights):
    """handle_coalesced (the burst inbox drive run() uses under
    coalesce=True) commits the same chain as per-message handle()."""
    commits_a: dict = {}
    rep_a = _build(commits=commits_a)
    rep_a.start()
    script = _script(heights)
    for m in script:
        rep_a.handle(m)

    commits_b: dict = {}
    rep_b = _build(commits=commits_b)
    rep_b.start()
    rep_b.handle_coalesced(script)
    assert commits_a == commits_b and set(commits_a) == {1, 2}


# ---------------------------------------------------------------- fast path
# The double-buffered flush and the wire-facing columnar settle need a
# verifier with the async begin/mask protocol, so these run the real
# TpuWireVerifier (CPU; bucket shapes shared with test_ed25519_wire so
# the suite pays no extra compile) over ring-signed scripts.

from hyperdrive_tpu.batch import MessageBlock  # noqa: E402
from hyperdrive_tpu.crypto import ed25519 as host_ed  # noqa: E402
from hyperdrive_tpu.crypto.keys import KeyRing  # noqa: E402
from hyperdrive_tpu.ops.ed25519_wire import TpuWireVerifier  # noqa: E402
from hyperdrive_tpu.verifier import HostVerifier  # noqa: E402

RING = KeyRing.deterministic(N, namespace=b"flushfast")
RSIGS = RING.signatories


def _signed(m, kp):
    return m.with_signature(host_ed.sign(kp.seed, m.digest()))


_SCRIPT_CACHE: dict = {}


def _signed_script(heights):
    """Ring-signed clean run; proposers are validators 1..3 only (this
    replica's own loopback votes are unsigned and verify-rejected, so
    quorum comes from the other 2f+1 = 3 — itself a useful property).
    Cached: pure-Python signing dominates these tests otherwise."""
    if heights in _SCRIPT_CACHE:
        return _SCRIPT_CACHE[heights]
    msgs = []
    for h in range(1, heights + 1):
        i_prop = h % N
        v = _value(h, 0)
        if i_prop != 0:
            msgs.append(_signed(Propose(height=h, round=0,
                                        valid_round=INVALID_ROUND, value=v,
                                        sender=RSIGS[i_prop]),
                                RING[i_prop]))
        for i in range(1, N):
            msgs.append(_signed(Prevote(height=h, round=0, value=v,
                                        sender=RSIGS[i]), RING[i]))
        for i in range(1, N):
            msgs.append(_signed(Precommit(height=h, round=0, value=v,
                                          sender=RSIGS[i]), RING[i]))
    _SCRIPT_CACHE[heights] = msgs
    return msgs


_WIRE = None


def _wire_verifier():
    """One TpuWireVerifier per process: per-instance warmup/compile state
    is the expensive part, and launches are independent across flushers
    (the shared-Verifier deployment shape)."""
    global _WIRE
    if _WIRE is None:
        _WIRE = TpuWireVerifier(buckets=(16, 64))
    return _WIRE


def _build_signed(fl, commits):
    lb = _Loopback()
    rep = Replica(
        ReplicaOptions(),
        whoami=RSIGS[0],
        signatories=list(RSIGS),
        timer=None,
        proposer=MockProposer(fn=_value),
        validator=MockValidator(ok=True),
        committer=CommitterCallback(
            on_commit=lambda h, v: (commits.__setitem__(h, v), (0, None))[1]
        ),
        catcher=None,
        broadcaster=lb,
        verifier=None,
        flusher=fl,
    )
    lb.rep = rep
    return rep


def _drive_mq(split):
    """Feed each height's signed window into the mq, flush, return the
    committed chain. ``split`` is the flusher's pipeline_split."""
    commits: dict = {}
    fl = DeviceTallyFlusher(_wire_verifier(), RSIGS,
                            pipeline_split=split)
    fl.warmup()
    rep = _build_signed(fl, commits)
    for h in range(1, 4):
        for m in _signed_script(3):
            if m.height == h:
                if isinstance(m, Propose):
                    rep.mq.insert_propose(m)
                elif isinstance(m, Prevote):
                    rep.mq.insert_prevote(m)
                else:
                    rep.mq.insert_precommit(m)
        fl.flush(rep)
    return commits, rep


def test_flusher_split_window_matches_single_launch():
    """pipeline_split=4 makes every 7-message window verify as two
    overlapped launches (half 2 in flight during half 1's host insert);
    the committed chain must equal the monolithic schedule's exactly."""
    c_split, rep_split = _drive_mq(split=4)
    c_mono, rep_mono = _drive_mq(split=0)
    assert c_split == c_mono and set(c_split) == {1, 2, 3}
    assert rep_split.proc.current_height == rep_mono.proc.current_height == 4


def test_settle_block_columnar_matches_object_path():
    """The wire-facing entry: a MessageBlock window settles through the
    columnar fast path to the same chain as the object mq path, and the
    fastpath row counter proves the columnar leg actually ran."""
    c_mono, _ = _drive_mq(split=0)
    commits: dict = {}
    fl = DeviceTallyFlusher(_wire_verifier(), RSIGS)
    fl.warmup()
    rep = _build_signed(fl, commits)
    for h in range(1, 4):
        window = [m for m in _signed_script(3) if m.height == h]
        fl.settle_block(rep, MessageBlock.from_messages(window))
    assert commits == c_mono and set(commits) == {1, 2, 3}
    assert fl.fastpath_rows == 21  # 3 heights x (1 propose + 6 votes)


def test_settle_block_sync_verifier_fallback():
    """settle_block with a begin-less verifier (HostVerifier) takes the
    synchronous verify leg and still commits the same chain."""
    c_mono, _ = _drive_mq(split=0)
    commits: dict = {}
    fl = DeviceTallyFlusher(HostVerifier(), RSIGS)
    fl.warmup()
    rep = _build_signed(fl, commits)
    for h in range(1, 4):
        window = [m for m in _signed_script(3) if m.height == h]
        fl.settle_block(rep, MessageBlock.from_messages(window))
    assert commits == c_mono and set(commits) == {1, 2, 3}


def test_queue_mode_commits_match_blocking_flush():
    """The devsched seam: a flusher given ``queue=`` submits its windows
    instead of verifying inline, and dispatch happens at the queue's
    drain — by which point co-submitted windows coalesced into one
    launch. The committed chain must equal the blocking flush's."""
    from hyperdrive_tpu.devsched import DeviceWorkQueue

    queue = DeviceWorkQueue()
    commits_q: dict = {}
    fq = DeviceTallyFlusher(NullVerifier(), SIGS, queue=queue)
    rep_q = _build(flusher=fq, commits=commits_q)
    commits_host: dict = {}
    rep_host = _build(commits=commits_host)

    rep_q.start()
    rep_host.start()
    for m in _script(3):
        rep_q.handle(m)
        rep_host.handle(m)
        queue.drain()  # the deployment event loop's idle hook
    assert commits_q == commits_host
    assert len(commits_q) >= 3
    assert queue.submitted > 0 and queue.depth == 0


def test_queue_mode_reset_cancels_inflight_windows():
    """Crash-restart recovery: Replica.restore() must not let the dead
    incarnation's in-flight windows dispatch on top of the checkpoint —
    reset() cancels them at the queue."""
    from hyperdrive_tpu.devsched import DeviceWorkQueue
    from hyperdrive_tpu.utils.checkpoint import checkpoint_bytes

    queue = DeviceWorkQueue()
    commits: dict = {}
    fl = DeviceTallyFlusher(NullVerifier(), SIGS, queue=queue)
    rep = _build(flusher=fl, commits=commits)
    rep.start()
    ckpt = checkpoint_bytes(rep.proc)
    for m in _script(2):
        rep.handle(m)  # no drain: windows pile up in flight
    inflight = list(fl._inflight)
    assert inflight, "expected undrained windows in flight"
    rep.restore(ckpt)
    assert not fl._inflight
    assert all(f.cancelled() for f in inflight)
    # The cancelled windows never dispatch; the revived replica rebuilds
    # from live traffic and commits the same chain.
    queue.drain()
    commits.clear()
    for m in _script(2):
        rep.handle(m)
        queue.drain()
    commits_host: dict = {}
    rep_host = _build(commits=commits_host)
    rep_host.start()
    for m in _script(2):
        rep_host.handle(m)
    assert commits == commits_host
