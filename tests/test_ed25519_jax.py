"""Device Ed25519 verification: differential tests against the host oracle.

The device kernel and the pure-Python RFC 8032 implementation must agree
accept/reject on every input — valid signatures, corrupted signatures,
wrong keys, malformed points, out-of-range scalars, unsigned messages.
"""

import numpy as np
import pytest

from hyperdrive_tpu.crypto import ed25519 as host_ed
from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier
from hyperdrive_tpu.verifier import HostVerifier


@pytest.fixture(scope="module")
def verifier():
    return TpuBatchVerifier(buckets=(16, 64))


@pytest.fixture(scope="module")
def ring():
    return KeyRing.deterministic(8, namespace=b"devtest")


def test_valid_signatures_accepted(verifier, ring, rng):
    items = []
    for i in range(10):
        kp = ring[i % len(ring)]
        msg = bytes([i]) * 24
        items.append((kp.public, msg, host_ed.sign(kp.seed, msg)))
    ok = verifier.verify_signatures(items)
    assert ok.tolist() == [True] * 10


def test_rejections_match_host(verifier, ring, rng):
    kp = ring[0]
    msg = b"attack at dawn"
    sig = host_ed.sign(kp.seed, msg)

    cases = [
        (kp.public, msg, sig),  # valid
        (kp.public, msg + b"!", sig),  # wrong message
        (kp.public, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]),  # bad s
        (kp.public, msg, bytes([sig[0] ^ 1]) + sig[1:]),  # bad R
        (ring[1].public, msg, sig),  # wrong key
        (b"\xff" * 32, msg, sig),  # invalid pubkey point
        (kp.public, msg, b"\xff" * 32 + sig[32:]),  # invalid R point
        (
            kp.public,
            msg,
            sig[:32]
            + int.to_bytes(
                int.from_bytes(sig[32:], "little") + host_ed.L, 32, "little"
            ),
        ),  # s >= L (malleability)
    ]
    got = verifier.verify_signatures(cases).tolist()
    want = [host_ed.verify(pub, m, s) for pub, m, s in cases]
    assert got == want
    assert want == [True] + [False] * 7


def test_random_differential(verifier, ring, rng):
    # Random mix of valid/corrupted; device must match host bit-for-bit.
    items = []
    for _i in range(32):
        kp = ring[rng.randrange(len(ring))]
        msg = rng.randbytes(rng.randint(0, 64))
        sig = host_ed.sign(kp.seed, msg)
        roll = rng.random()
        if roll < 0.3:
            sig = bytearray(sig)
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(sig)
        elif roll < 0.4:
            msg = msg + b"x"
        items.append((kp.public, msg, sig))
    got = verifier.verify_signatures(items).tolist()
    want = [host_ed.verify(p, m, s) for p, m, s in items]
    assert got == want


def test_pack_empty_batch(verifier):
    # Regression: the dedup fan-out condition 2*len(uniq) <= n held for
    # n == 0 and recursed with the same empty list forever.
    arrays, prevalid, n = verifier.host.pack([])
    assert n == 0
    assert not prevalid.any()
    assert arrays[0].shape[0] == verifier.host.buckets[0]
    assert verifier.verify_signatures([]).tolist() == []


def test_batch_padding_buckets(verifier, ring):
    # 1 item in a 16-bucket, 17 items in a 64-bucket: padding lanes must
    # not leak into results.
    kp = ring[0]
    one = [(kp.public, b"m", host_ed.sign(kp.seed, b"m"))]
    assert verifier.verify_signatures(one).tolist() == [True]
    many = one * 17
    assert verifier.verify_signatures(many).tolist() == [True] * 17


def test_verifier_protocol_matches_host_verifier(verifier, ring):
    hv = HostVerifier()
    msgs = []
    for i in range(6):
        kp = ring[i]
        pv = Prevote(height=1, round=0, value=bytes([i]) * 32, sender=kp.public)
        if i % 3 == 0:
            msgs.append(kp.sign_message(pv))  # valid
        elif i % 3 == 1:
            msgs.append(pv)  # unsigned
        else:
            msgs.append(pv.with_signature(b"\x01" * 64))  # garbage sig
    assert verifier.verify_batch(msgs) == hv.verify_batch(msgs)


# ------------------------------------------------------ RLC batch equation


@pytest.fixture(scope="module")
def rlc_verifier():
    return TpuBatchVerifier(buckets=(16, 64), rlc=True)


def _signed_items(ring, n, tag=0):
    items = []
    for i in range(n):
        kp = ring[i % len(ring)]
        msg = bytes([i, tag]) * 16
        items.append((kp.public, msg, host_ed.sign(kp.seed, msg)))
    return items


def test_rlc_accepts_valid_batch_without_fallback(rlc_verifier, ring):
    items = _signed_items(ring, 16)
    ok = rlc_verifier.verify_signatures(items)
    assert ok.tolist() == [True] * 16
    assert rlc_verifier.rlc_fallbacks == 0


def test_rlc_fallback_localizes_forgery(rlc_verifier, ring):
    items = _signed_items(ring, 16, tag=1)
    s = items[7][2]
    items[7] = (items[7][0], items[7][1], s[:40] + bytes([s[40] ^ 1]) + s[41:])
    before = rlc_verifier.rlc_fallbacks
    ok = rlc_verifier.verify_signatures(items)
    assert ok.tolist() == [i != 7 for i in range(16)]
    assert rlc_verifier.rlc_fallbacks == before + 1


def test_rlc_malformed_lanes_skip_fallback(rlc_verifier, ring):
    # Host-rejected lanes (bad point, wrong-length sig) are excluded from
    # the combined equation entirely: the batch of remaining valid lanes
    # still passes in one launch, and the malformed lanes read False.
    items = _signed_items(ring, 8, tag=2)
    items[2] = (b"\xff" * 32, items[2][1], items[2][2])
    items[5] = (items[5][0], items[5][1], b"\x01" * 63)
    before = rlc_verifier.rlc_fallbacks
    ok = rlc_verifier.verify_signatures(items)
    assert ok.tolist() == [i not in (2, 5) for i in range(8)]
    assert rlc_verifier.rlc_fallbacks == before


@pytest.mark.slow  # same differential at fixed vectors: the rlc tests
# above + the wire/chal suites; randomized sweep stays in the full pass
def test_rlc_differential_random(rlc_verifier, ring, rng):
    items = []
    for _i in range(24):
        kp = ring[rng.randrange(len(ring))]
        msg = rng.randbytes(rng.randint(0, 48))
        sig = host_ed.sign(kp.seed, msg)
        if rng.random() < 0.25:
            sig = bytearray(sig)
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
            sig = bytes(sig)
        items.append((kp.public, msg, sig))
    got = rlc_verifier.verify_signatures(items).tolist()
    want = [host_ed.verify(p, m, s) for p, m, s in items]
    assert got == want


def test_pack_dedups_repeated_triples_identically(ring):
    # A duplicate-heavy batch (every receiver re-verifying the same
    # broadcasts) must pack each distinct triple once and fan the rows
    # out — bit-identical to packing the expanded list row by row.
    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.ops.ed25519_jax import Ed25519BatchHost

    host = Ed25519BatchHost(buckets=(16, 64))
    base = []
    for v in range(4):
        d = bytes([v]) * 32
        base.append((ring[v].public, d, host_ed.sign(ring[v].seed, d)))
    base.append((b"\xff" * 32, b"\x00" * 32, b"\x01" * 64))  # malformed
    repeated = base * 3 + base[:2]

    arrays_r, prevalid_r, n_r = host.pack(repeated)
    assert n_r == len(repeated)
    # Reference: pack each item alone (no dedup possible) and compare rows.
    for i, it in enumerate(repeated):
        arrays_1, prevalid_1, _ = host.pack([it])
        for a_r, a_1 in zip(arrays_r, arrays_1):
            np.testing.assert_array_equal(a_r[i], a_1[0])
        assert bool(prevalid_r[i]) == bool(prevalid_1[0])


def test_verify_signatures_redundant_batch_matches_host(verifier, ring):
    # A duplicate-heavy batch rides the device-expansion path (unique
    # rows + gather index shipped, full ladder on every lane); verdicts
    # must equal both the per-unique verdicts fanned out and the host
    # oracle, including forged and malformed lanes.
    from hyperdrive_tpu.crypto import ed25519 as host_ed
    from hyperdrive_tpu.verifier import HostVerifier

    base = []
    for v in range(5):
        d = bytes([v + 1]) * 32
        sig = host_ed.sign(ring[v].seed, d)
        if v == 2:  # forged lane: parses, must reject on device
            sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
        base.append((ring[v].public, d, sig))
    base.append((b"\xff" * 32, b"\x07" * 32, b"\x01" * 64))  # malformed
    repeated = base * 13  # 78 items, 6 unique -> dedup path engages
    got = np.asarray(verifier.verify_signatures(repeated))
    unique = np.asarray(verifier.verify_signatures(base))
    np.testing.assert_array_equal(got, np.tile(unique, 13))
    host = np.asarray(HostVerifier().verify_signatures(repeated))
    np.testing.assert_array_equal(got, host)
    assert got.any() and not got.all()


def test_wrong_length_signatures_reject_deterministically(verifier, ring):
    # Wrong-length signatures must be structurally rejected on every path
    # (never zero-padded and verified: with an adversarial small-order
    # pubkey a zero signature can probabilistically verify). Host native,
    # host Python, and device paths must all agree: deterministic False.
    hv = HostVerifier()
    kp = ring[0]
    msgs = []
    for n in (0, 1, 32, 63, 65, 128):
        pv = Prevote(height=1, round=0, value=bytes([n % 256]) * 32, sender=kp.public)
        msgs.append(pv.with_signature(b"\x07" * n))
    # One valid message so the batch isn't all-rejected.
    good = Prevote(height=1, round=0, value=b"\x2a" * 32, sender=kp.public)
    msgs.append(kp.sign_message(good))

    want = [False] * 6 + [True]
    assert hv.verify_batch(msgs) == want
    assert verifier.verify_batch(msgs) == want
