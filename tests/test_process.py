"""Rule-by-rule consensus automaton specs.

Mirrors the reference's 4k-line process_test.go strategy: drive a raw
Process with recording mocks and assert on the broadcast/commit/timeout/
catch side effects for each paper rule (L11..L65), plus insert validation,
equivocation catching, and checkpoint serde.
"""

from types import SimpleNamespace

import pytest

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import Precommit, Prevote, Propose
from hyperdrive_tpu.process import Process
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockScheduler,
    MockValidator,
    TimerCallbacks,
)
from hyperdrive_tpu.types import INVALID_ROUND, NIL_VALUE, Step


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def val(i: int) -> bytes:
    return bytes([0xA0 + i]) * 32


WHOAMI = sig(1)
PROPOSER = sig(2)
OTHER_A = sig(3)
OTHER_B = sig(4)
OTHER_C = sig(5)


def make_process(
    whoami=WHOAMI,
    f=1,
    proposer_sig=PROPOSER,
    proposer_value=None,
    validator_ok=True,
    height=1,
):
    """A Process wired to recording mocks; the scheduled proposer for every
    (height, round) is ``proposer_sig``."""
    rec = SimpleNamespace(
        proposes=[], prevotes=[], precommits=[], commits=[],
        timeout_proposes=[], timeout_prevotes=[], timeout_precommits=[],
        double_proposes=[], double_prevotes=[], double_precommits=[],
        out_of_turns=[],
    )
    commit_return = {"f": 0, "scheduler": None}

    proc = Process(
        whoami=whoami,
        f=f,
        timer=TimerCallbacks(
            on_propose=lambda h, r: rec.timeout_proposes.append((h, r)),
            on_prevote=lambda h, r: rec.timeout_prevotes.append((h, r)),
            on_precommit=lambda h, r: rec.timeout_precommits.append((h, r)),
        ),
        scheduler=MockScheduler(proposer_sig),
        proposer=MockProposer(value=proposer_value or val(0)),
        validator=MockValidator(ok=validator_ok),
        broadcaster=BroadcasterCallbacks(
            on_propose=rec.proposes.append,
            on_prevote=rec.prevotes.append,
            on_precommit=rec.precommits.append,
        ),
        committer=CommitterCallback(
            on_commit=lambda h, v: (
                rec.commits.append((h, v)),
                (commit_return["f"], commit_return["scheduler"]),
            )[1]
        ),
        catcher=CatcherCallbacks(
            on_double_propose=lambda a, b: rec.double_proposes.append((a, b)),
            on_double_prevote=lambda a, b: rec.double_prevotes.append((a, b)),
            on_double_precommit=lambda a, b: rec.double_precommits.append((a, b)),
            on_out_of_turn_propose=rec.out_of_turns.append,
        ),
        height=height,
    )
    return proc, rec, commit_return


def prevote(sender, value, round=0, height=1):
    return Prevote(height=height, round=round, value=value, sender=sender)


def precommit(sender, value, round=0, height=1):
    return Precommit(height=height, round=round, value=value, sender=sender)


def propose(value, round=0, height=1, valid_round=INVALID_ROUND, sender=PROPOSER):
    return Propose(height=height, round=round, valid_round=valid_round,
                   value=value, sender=sender)


# ------------------------------------------------------------- L11 StartRound


class TestStartRound:
    def test_non_proposer_schedules_propose_timeout(self):
        proc, rec, _ = make_process()
        proc.start()
        assert proc.current_round == 0
        assert proc.current_step == Step.PROPOSING
        assert rec.timeout_proposes == [(1, 0)]
        assert rec.proposes == []

    def test_proposer_broadcasts_fresh_value(self):
        proc, rec, _ = make_process(whoami=PROPOSER, proposer_value=val(7))
        proc.start()
        assert len(rec.proposes) == 1
        p = rec.proposes[0]
        assert (p.height, p.round, p.valid_round) == (1, 0, INVALID_ROUND)
        assert p.value == val(7)
        assert p.sender == PROPOSER
        assert rec.timeout_proposes == []

    def test_proposer_reproposes_valid_value(self):
        proc, rec, _ = make_process(whoami=PROPOSER, proposer_value=val(7))
        proc.state.valid_value = val(9)
        proc.state.valid_round = 2
        proc.start_round(3)
        p = rec.proposes[0]
        assert p.value == val(9)
        assert p.valid_round == 2
        assert p.round == 3

    def test_no_scheduler_does_nothing(self):
        proc, rec, _ = make_process()
        proc.scheduler = None
        proc.start()
        assert rec.proposes == [] and rec.timeout_proposes == []


# ----------------------------------------------------------- timeout handlers


class TestTimeouts:
    def test_on_timeout_propose_prevotes_nil(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)
        assert len(rec.prevotes) == 1
        assert rec.prevotes[0].value == NIL_VALUE
        assert proc.current_step == Step.PREVOTING

    @pytest.mark.parametrize("h,r", [(2, 0), (1, 1)])
    def test_on_timeout_propose_wrong_coords_ignored(self, h, r):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(h, r)
        assert rec.prevotes == []
        assert proc.current_step == Step.PROPOSING

    def test_on_timeout_propose_wrong_step_ignored(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)
        rec.prevotes.clear()
        proc.on_timeout_propose(1, 0)  # now Prevoting; must not fire again
        assert rec.prevotes == []

    def test_on_timeout_prevote_precommits_nil(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)
        proc.on_timeout_prevote(1, 0)
        assert len(rec.precommits) == 1
        assert rec.precommits[0].value == NIL_VALUE
        assert proc.current_step == Step.PRECOMMITTING

    def test_on_timeout_prevote_wrong_step_ignored(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_prevote(1, 0)  # still Proposing
        assert rec.precommits == []

    def test_on_timeout_precommit_starts_next_round(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_precommit(1, 0)
        assert proc.current_round == 1
        assert proc.current_step == Step.PROPOSING
        # New round schedules a fresh propose timeout for round 1.
        assert (1, 1) in rec.timeout_proposes

    def test_on_timeout_precommit_wrong_coords_ignored(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_precommit(1, 5)
        proc.on_timeout_precommit(9, 0)
        assert proc.current_round == 0


# ------------------------------------------------------------------------ L22


class TestPrevoteUponPropose:
    def test_valid_fresh_propose_prevoted(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        assert len(rec.prevotes) == 1
        assert rec.prevotes[0].value == val(1)
        assert proc.current_step == Step.PREVOTING

    def test_invalid_propose_prevotes_nil(self):
        proc, rec, _ = make_process(validator_ok=False)
        proc.start()
        proc.propose(propose(val(1)))
        assert rec.prevotes[0].value == NIL_VALUE
        assert proc.current_step == Step.PREVOTING

    def test_nil_value_propose_prevotes_nil(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(NIL_VALUE))
        assert rec.prevotes[0].value == NIL_VALUE

    def test_locked_on_other_value_prevotes_nil(self):
        proc, rec, _ = make_process()
        proc.state.locked_value = val(9)
        proc.state.locked_round = 0
        proc.start()
        proc.propose(propose(val(1)))
        assert rec.prevotes[0].value == NIL_VALUE

    def test_locked_on_same_value_prevotes_value(self):
        proc, rec, _ = make_process()
        proc.state.locked_value = val(1)
        proc.state.locked_round = 0
        proc.start()
        proc.propose(propose(val(1)))
        assert rec.prevotes[0].value == val(1)

    def test_repropose_with_valid_round_not_l22(self):
        # A propose carrying a ValidRound is the L28 rule's job.
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1), valid_round=0, round=0))
        # vr=0 >= current round 0, so neither L22 nor L28 fires.
        assert rec.prevotes == []
        assert proc.current_step == Step.PROPOSING


# ------------------------------------------------------------------------ L28


class TestPrevoteUponSufficientPrevotes:
    def _arm(self, proc):
        """Move to round 1 while keeping step Proposing."""
        proc.start()
        proc.on_timeout_precommit(1, 0)
        assert (proc.current_round, proc.current_step) == (1, Step.PROPOSING)

    def test_repropose_with_quorum_at_valid_round(self):
        proc, rec, _ = make_process()
        self._arm(proc)
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1), round=0))
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert [pv.value for pv in rec.prevotes] == [val(1)]
        assert rec.prevotes[0].round == 1
        assert proc.current_step == Step.PREVOTING

    def test_insufficient_quorum_no_prevote(self):
        proc, rec, _ = make_process()
        self._arm(proc)
        for s in (OTHER_A, OTHER_B):
            proc.prevote(prevote(s, val(1), round=0))
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert rec.prevotes == []
        assert proc.current_step == Step.PROPOSING

    def test_quorum_for_different_value_no_prevote(self):
        proc, rec, _ = make_process()
        self._arm(proc)
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(2), round=0))
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert rec.prevotes == []

    def test_invalid_propose_with_quorum_prevotes_nil(self):
        proc, rec, _ = make_process(validator_ok=False)
        self._arm(proc)
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1), round=0))
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert [pv.value for pv in rec.prevotes] == [NIL_VALUE]

    def test_locked_above_valid_round_prevotes_nil(self):
        proc, rec, _ = make_process()
        self._arm(proc)
        proc.state.locked_value = val(9)
        proc.state.locked_round = 1
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1), round=0))
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert [pv.value for pv in rec.prevotes] == [NIL_VALUE]


class TestLockLifecycleAcrossRounds:
    """The paper's locking discipline driven through real message flow
    (no state poking): lock, carry the lock across rounds, release it via
    a quorum at a later valid_round, re-lock, and clear on commit.
    Reference scenarios: process_test.go lock-and-precommit and
    re-propose contexts (1879-2221, 1170-1589)."""

    def _lock_at_round_0(self, proc, rec, value):
        proc.propose(propose(value))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, value))
        assert proc.state.locked_value == value
        assert proc.state.locked_round == 0
        assert [pc.value for pc in rec.precommits] == [value]
        assert proc.current_step == Step.PRECOMMITTING

    def test_lock_carries_to_next_round_fresh_proposal_prevotes_nil(self):
        proc, rec, _ = make_process()
        proc.start()
        self._lock_at_round_0(proc, rec, val(1))
        # Round 0 fails to commit; round 1 proposer offers a DIFFERENT
        # fresh value — the lock forces a nil prevote (L22 lockable check).
        proc.on_timeout_precommit(1, 0)
        proc.propose(propose(val(2), round=1))
        assert rec.prevotes[-1].value == NIL_VALUE
        assert rec.prevotes[-1].round == 1
        assert proc.state.locked_value == val(1)  # lock intact

    def test_lock_releases_for_repropose_at_lockeds_own_round(self):
        proc, rec, _ = make_process()
        proc.start()
        self._lock_at_round_0(proc, rec, val(1))
        proc.on_timeout_precommit(1, 0)
        # Round 1 re-proposes the SAME value with valid_round=0; the round-0
        # prevote quorum already sits in the logs, so L28 fires and the
        # lock (locked_round 0 <= vr 0) allows prevoting it again.
        proc.propose(propose(val(1), round=1, valid_round=0))
        assert rec.prevotes[-1].value == val(1)
        assert rec.prevotes[-1].round == 1

    def test_relock_on_newer_quorum(self):
        proc, rec, _ = make_process()
        proc.start()
        self._lock_at_round_0(proc, rec, val(1))
        # No commit at rounds 0-1; at round 1 a fresh proposal val(2)
        # gains its own prevote quorum while we are prevoting: L36 must
        # RE-lock onto the newer (round, value) pair.
        proc.on_timeout_precommit(1, 0)
        proc.propose(propose(val(2), round=1))  # lock forces nil prevote
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(2), round=1))
        assert proc.state.locked_value == val(2)
        assert proc.state.locked_round == 1
        assert rec.precommits[-1].value == val(2)
        assert rec.precommits[-1].round == 1

    def test_valid_value_updates_without_lock_when_past_prevoting(self):
        proc, rec, _ = make_process()
        proc.start()
        # Reach PRECOMMITTING via the nil path (no lock taken): propose is
        # missing, 2f+1 nil prevotes fire L44.
        proc.on_timeout_propose(1, 0)  # broadcast nil prevote -> Prevoting
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, NIL_VALUE))
        assert proc.current_step == Step.PRECOMMITTING
        assert proc.state.locked_round == INVALID_ROUND
        # Now the proposal arrives late with a value quorum from OTHER
        # senders (the first three already prevoted nil; duplicates would
        # be equivocation): L36 runs with step past Prevoting —
        # valid_value/round update, but no lock and no second precommit.
        n_precommits = len(rec.precommits)
        proc.propose(propose(val(3)))
        for s in (sig(9), sig(10), sig(11)):
            proc.prevote(prevote(s, val(3)))
        assert proc.state.valid_value == val(3)
        assert proc.state.valid_round == 0
        assert proc.state.locked_round == INVALID_ROUND
        assert len(rec.precommits) == n_precommits

    def test_commit_clears_lock_for_next_height(self):
        proc, rec, _ = make_process()
        proc.start()
        self._lock_at_round_0(proc, rec, val(1))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.precommit(precommit(s, val(1)))
        assert rec.commits == [(1, val(1))]
        assert proc.current_height == 2
        assert proc.state.locked_value == NIL_VALUE
        assert proc.state.locked_round == INVALID_ROUND
        assert proc.state.valid_round == INVALID_ROUND


# ------------------------------------------------------------------------ L34


class TestTimeoutPrevoteUponSufficientPrevotes:
    def test_quorum_of_any_prevotes_schedules_timeout_once(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)  # -> Prevoting
        proc.prevote(prevote(OTHER_A, val(1)))
        proc.prevote(prevote(OTHER_B, val(2)))
        assert rec.timeout_prevotes == []
        proc.prevote(prevote(OTHER_C, NIL_VALUE))
        assert rec.timeout_prevotes == [(1, 0)]
        proc.prevote(prevote(PROPOSER, val(3)))
        assert rec.timeout_prevotes == [(1, 0)]  # once per round

    def test_not_scheduled_while_proposing(self):
        proc, rec, _ = make_process()
        proc.start()
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1)))
        # Step is still Proposing (no propose seen): L34 must not fire...
        assert rec.timeout_prevotes == []
        # ...but L36 must also not have fired (no propose); check step intact.
        assert proc.current_step == Step.PROPOSING


# ------------------------------------------------------------------------ L36


class TestPrecommitUponSufficientPrevotes:
    def test_lock_and_precommit(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))  # L22: prevote + step Prevoting
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1)))
        assert [pc.value for pc in rec.precommits] == [val(1)]
        assert proc.current_step == Step.PRECOMMITTING
        assert proc.state.locked_value == val(1)
        assert proc.state.locked_round == 0
        assert proc.state.valid_value == val(1)
        assert proc.state.valid_round == 0

    def test_once_per_round(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1)))
        proc.prevote(prevote(PROPOSER, val(1)))
        assert len(rec.precommits) == 1

    def test_updates_valid_value_when_already_precommitting(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)  # -> Prevoting (nil prevote)
        proc.on_timeout_prevote(1, 0)  # -> Precommitting (nil precommit)
        rec.precommits.clear()
        proc.propose(propose(val(1)))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1)))
        # Step was already Precommitting: no new precommit, no lock...
        assert rec.precommits == []
        assert proc.state.locked_round == INVALID_ROUND
        # ...but the valid value/round are still recorded.
        assert proc.state.valid_value == val(1)
        assert proc.state.valid_round == 0

    def test_requires_valid_propose(self):
        proc, rec, _ = make_process(validator_ok=False)
        proc.start()
        proc.propose(propose(val(1)))  # L22 prevotes nil -> Prevoting
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1)))
        assert rec.precommits == []
        assert proc.state.locked_round == INVALID_ROUND


# ------------------------------------------------------------------------ L44


class TestPrecommitNilUponSufficientPrevotes:
    def test_nil_quorum_precommits_nil(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)  # -> Prevoting
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, NIL_VALUE))
        assert [pc.value for pc in rec.precommits] == [NIL_VALUE]
        assert proc.current_step == Step.PRECOMMITTING

    def test_mixed_values_do_not_count(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_propose(1, 0)
        proc.prevote(prevote(OTHER_A, NIL_VALUE))
        proc.prevote(prevote(OTHER_B, val(1)))
        proc.prevote(prevote(OTHER_C, NIL_VALUE))
        assert rec.precommits == []


# ------------------------------------------------------------------------ L47


class TestTimeoutPrecommitUponSufficientPrecommits:
    def test_exactly_quorum_schedules_timeout_once(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.precommit(precommit(OTHER_A, val(1)))
        proc.precommit(precommit(OTHER_B, NIL_VALUE))
        assert rec.timeout_precommits == []
        proc.precommit(precommit(OTHER_C, val(2)))
        assert rec.timeout_precommits == [(1, 0)]
        proc.precommit(precommit(PROPOSER, val(1)))
        assert rec.timeout_precommits == [(1, 0)]

    def test_batched_ingest_jumping_past_quorum_still_schedules(self):
        """Regression: a window can push the distinct-precommit count from
        0 straight past 2f+1; the (once-flagged) check must be >= or the
        timeout is never scheduled and round 0 stalls forever."""
        proc, rec, _ = make_process()
        proc.start()
        proc.ingest([
            precommit(OTHER_A, val(1)),
            precommit(OTHER_B, NIL_VALUE),
            precommit(OTHER_C, val(2)),
            precommit(PROPOSER, NIL_VALUE),  # count 0 -> 4, skips ==3
        ])
        assert rec.timeout_precommits == [(1, 0)]


class TestBatchedIngest:
    def test_full_round_window_commits(self):
        """One ingest of an entire round's traffic (propose + prevote and
        precommit quorums) commits and advances the height, exactly like
        serial delivery."""
        proc, rec, _ = make_process()
        proc.start()
        msgs = [propose(val(1))]
        msgs += [prevote(s, val(1)) for s in (OTHER_A, OTHER_B, OTHER_C)]
        msgs += [precommit(s, val(1)) for s in (OTHER_A, OTHER_B, OTHER_C)]
        proc.ingest(msgs)
        assert rec.commits == [(1, val(1))]
        assert proc.current_height == 2

    def test_future_round_skip_from_window(self):
        """f+1 distinct senders at a future round inside one window fire
        the L55 skip."""
        proc, rec, _ = make_process()
        proc.start()
        proc.ingest([
            prevote(OTHER_A, val(1), round=3),
            prevote(OTHER_B, NIL_VALUE, round=3),
        ])
        assert proc.state.current_round == 3

    def test_empty_and_rejected_windows_are_noops(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.ingest([])
        proc.ingest([prevote(OTHER_A, val(1), height=9)])  # wrong height
        assert proc.current_height == 1
        assert rec.commits == []


# ------------------------------------------------------------------------ L49


class TestCommitUponSufficientPrecommits:
    def test_commit_advances_height(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.precommit(precommit(s, val(1)))
        assert rec.commits == [(1, val(1))]
        assert proc.current_height == 2
        assert proc.current_round == 0
        assert proc.current_step == Step.PROPOSING
        assert proc.state.locked_round == INVALID_ROUND
        assert proc.state.valid_round == INVALID_ROUND
        assert not proc.state.propose_logs
        # The new height's round 0 scheduled its propose timeout.
        assert (2, 0) in rec.timeout_proposes

    def test_commit_requires_valid_propose(self):
        proc, rec, _ = make_process(validator_ok=False)
        proc.start()
        proc.propose(propose(val(1)))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.precommit(precommit(s, val(1)))
        assert rec.commits == []
        assert proc.current_height == 1

    def test_commit_requires_matching_values(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        proc.precommit(precommit(OTHER_A, val(1)))
        proc.precommit(precommit(OTHER_B, val(2)))
        proc.precommit(precommit(OTHER_C, val(1)))
        assert rec.commits == []

    def test_commit_installs_rotated_validator_set(self):
        proc, rec, ret = make_process()
        new_sched = MockScheduler(OTHER_A)
        ret["f"] = 5
        ret["scheduler"] = new_sched
        proc.start()
        proc.propose(propose(val(1)))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.precommit(precommit(s, val(1)))
        assert proc.f == 5
        assert proc.scheduler is new_sched

    def test_commit_on_past_round(self):
        # Precommits for an earlier round still commit after a round skip.
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1), round=0))
        proc.on_timeout_precommit(1, 0)  # move to round 1
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.precommit(precommit(s, val(1), round=0))
        assert rec.commits == [(1, val(1))]
        assert proc.current_height == 2


# ------------------------------------------------------------------------ L55


class TestSkipToFutureRound:
    def test_f_plus_one_unique_signatories_skip(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.prevote(prevote(OTHER_A, val(1), round=5))
        assert proc.current_round == 0
        proc.precommit(precommit(OTHER_B, val(2), round=5))
        assert proc.current_round == 5
        assert proc.current_step == Step.PROPOSING
        assert (1, 5) in rec.timeout_proposes

    def test_same_signatory_counts_once(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.prevote(prevote(OTHER_A, val(1), round=5))
        proc.precommit(precommit(OTHER_A, val(1), round=5))
        assert proc.current_round == 0

    def test_invalid_propose_earns_no_trace_credit(self):
        proc, rec, _ = make_process(validator_ok=False)
        proc.start()
        proc.propose(propose(val(1), round=5))
        proc.prevote(prevote(OTHER_A, val(1), round=5))
        assert proc.current_round == 0  # invalid propose didn't count

    def test_valid_propose_earns_trace_credit(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1), round=5))
        proc.prevote(prevote(OTHER_A, val(1), round=5))
        assert proc.current_round == 5

    def test_past_round_never_skipped(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.on_timeout_precommit(1, 0)  # round 1
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc.prevote(prevote(s, val(1), round=0))
        assert proc.current_round == 1


# ----------------------------------------------------------- insert validation


class TestInserts:
    def test_wrong_height_propose_rejected(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1), height=9))
        assert not proc.state.propose_logs
        assert rec.prevotes == []

    def test_negative_round_propose_rejected(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1), round=-1))
        assert not proc.state.propose_logs

    def test_out_of_turn_propose_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        bad = propose(val(1), sender=OTHER_A)
        proc.propose(bad)
        assert rec.out_of_turns == [bad]
        assert not proc.state.propose_logs

    def test_double_propose_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        proc.propose(propose(val(2)))
        assert len(rec.double_proposes) == 1

    def test_identical_repropose_not_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        proc.propose(propose(val(1)))
        assert rec.double_proposes == []

    def test_double_prevote_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.prevote(prevote(OTHER_A, val(1)))
        proc.prevote(prevote(OTHER_A, val(2)))
        assert len(rec.double_prevotes) == 1
        # The first vote stands; the second is not logged.
        assert proc.state.prevote_logs[0][OTHER_A].value == val(1)

    def test_identical_prevote_not_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.prevote(prevote(OTHER_A, val(1)))
        proc.prevote(prevote(OTHER_A, val(1)))
        assert rec.double_prevotes == []

    def test_double_precommit_caught(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.precommit(precommit(OTHER_A, val(1)))
        proc.precommit(precommit(OTHER_A, val(2)))
        assert len(rec.double_precommits) == 1

    def test_wrong_height_votes_rejected(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.prevote(prevote(OTHER_A, val(1), height=3))
        proc.precommit(precommit(OTHER_A, val(1), height=0))
        assert not proc.state.prevote_logs
        assert not proc.state.precommit_logs


# ------------------------------------------------------------------ serde


class TestProcessSerde:
    def test_roundtrip(self):
        proc, rec, _ = make_process(f=3)
        proc.start()
        proc.propose(propose(val(1)))
        proc.prevote(prevote(OTHER_A, val(1)))
        w = Writer()
        proc.marshal(w)
        restored, _, _ = make_process()
        restored.unmarshal_into(Reader(w.data()))
        assert restored.whoami == proc.whoami
        assert restored.f == proc.f
        assert restored.state.equal(proc.state)
        assert restored.state.prevote_logs == proc.state.prevote_logs

    def test_fuzz_no_crash(self, rng):
        for _ in range(200):
            blob = rng.randbytes(rng.randint(0, 150))
            proc, _, _ = make_process()
            try:
                proc.unmarshal_into(Reader(blob))
            except SerdeError:
                pass

    def test_restored_process_keeps_making_progress(self):
        proc, rec, _ = make_process()
        proc.start()
        proc.propose(propose(val(1)))
        w = Writer()
        proc.marshal(w)

        # Restore into a fresh process with fresh mocks and finish the round.
        proc2, rec2, _ = make_process()
        proc2.unmarshal_into(Reader(w.data()))
        for s in (OTHER_A, OTHER_B, OTHER_C):
            proc2.precommit(precommit(s, val(1)))
        assert rec2.commits == [(1, val(1))]
        assert proc2.current_height == 2
