"""Threaded production-mode integration: real threads, real timers.

The reference's harness runs every replica on its own goroutine with
sleeping timers (replica_test.go:395-398); this is the analogue — n
replicas on real threads driven by Replica.run, LinearTimer at millisecond
timeouts, broadcasts fanned out through the thread-safe inboxes — asserting
the same safety obligation: byte-identical commit maps.
"""

import hashlib
import threading
import time

from hyperdrive_tpu.messages import Timeout
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockValidator,
)
from hyperdrive_tpu.timer import LinearTimer


def sig(i: int) -> bytes:
    return bytes([i + 1]) * 32


def value_for(height: int, round_: int) -> bytes:
    return hashlib.sha256(b"thr-%d-%d" % (height, round_)).digest()


class ThreadedNetwork:
    """n replicas on real threads; broadcasts go straight into every
    replica's inbox (including the sender's own).

    ``sign=True`` runs the full authenticated pipeline on threads: every
    broadcast is Ed25519-signed on its sender's thread and every replica
    verifies its drained windows through a HostVerifier — the threaded
    analogue of the harness's signed mode."""

    def __init__(self, n: int, target_height: int, timeout: float = 0.2,
                 offline: set | None = None, sign: bool = False):
        self.n = n
        self.target = target_height
        self.offline = offline or set()
        self.ring = None
        if sign:
            from hyperdrive_tpu.crypto.keys import KeyRing

            self.ring = KeyRing.deterministic(n, namespace=b"threaded")
            self.signatories = list(self.ring.signatories)
        else:
            self.signatories = [sig(i) for i in range(n)]
        self.commits = [dict() for _ in range(n)]
        self.done = [threading.Event() for _ in range(n)]
        self.stop = threading.Event()
        self.replicas: list[Replica] = []
        for i in range(n):
            self.replicas.append(self._build(i, timeout))

    def _build(self, i: int, timeout: float) -> Replica:
        keypair = self.ring[i] if self.ring is not None else None

        def bcast(msg):
            # Broadcast to all, including self, via the thread-safe inboxes
            # (reference: replica_test.go:174-208). Signed mode attaches
            # the sender's detached signature on the sender's own thread.
            if keypair is not None:
                msg = keypair.sign_message(msg)
            for j, r in enumerate(self.replicas_snapshot()):
                if j not in self.offline:
                    r._enqueue(msg, self.stop)

        def on_commit(h, v, i=i):
            self.commits[i][h] = v
            if h >= self.target:
                self.done[i].set()
            return (0, None)

        def on_timeout(t: Timeout, i=i):
            self.replicas_snapshot()[i]._enqueue(t, self.stop)

        timer = LinearTimer(
            handle_timeout_propose=on_timeout,
            handle_timeout_prevote=on_timeout,
            handle_timeout_precommit=on_timeout,
            timeout=timeout,
            timeout_scaling=0.5,
        )
        verifier = None
        if self.ring is not None:
            from hyperdrive_tpu.verifier import HostVerifier

            verifier = HostVerifier()
        return Replica(
            ReplicaOptions(),
            self.signatories[i],
            list(self.signatories),
            timer,
            MockProposer(fn=value_for),
            MockValidator(ok=True),
            CommitterCallback(on_commit=on_commit),
            CatcherCallbacks(),
            BroadcasterCallbacks(
                on_propose=bcast, on_prevote=bcast, on_precommit=bcast
            ),
            verifier=verifier,
        )

    def replicas_snapshot(self):
        return self.replicas

    def run(self, budget_s: float = 30.0) -> bool:
        threads = []
        for i, r in enumerate(self.replicas):
            if i in self.offline:
                continue
            t = threading.Thread(target=r.run, args=(self.stop,), daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + budget_s
        ok = True
        for i, ev in enumerate(self.done):
            if i in self.offline:
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ev.wait(remaining):
                ok = False
                break
        self.stop.set()
        for t in threads:
            t.join(timeout=5.0)
        return ok

    def assert_safety(self):
        for h in set().union(*[set(c) for c in self.commits]):
            vals = {c[h] for c in self.commits if h in c}
            assert len(vals) <= 1, f"fork at height {h}: {vals}"


def test_threaded_honest_network_commits_identically():
    net = ThreadedNetwork(n=4, target_height=5, timeout=0.5)
    assert net.run(budget_s=60.0), (
        f"threaded network stalled: heights="
    ) + str([r.current_height() for r in net.replicas])
    net.assert_safety()
    base = {h: v for h, v in net.commits[0].items() if h <= 5}
    assert set(base) >= set(range(1, 6))
    for c in net.commits[1:]:
        for h in range(1, 6):
            assert c.get(h) == base[h]


def test_threaded_signed_network_with_verifier():
    # Signing + batched window verification on real threads: every
    # broadcast carries a real Ed25519 signature made on the sender's
    # thread, every replica's flush drains windows through a HostVerifier.
    # Commit maps must still be byte-identical (the reference runs every
    # scenario on goroutines; this is the authenticated variant).
    net = ThreadedNetwork(n=4, target_height=4, timeout=0.5, sign=True)
    assert net.run(budget_s=60.0), (
        "signed threaded network stalled: heights="
    ) + str([r.current_height() for r in net.replicas])
    net.assert_safety()
    base = net.commits[0]
    assert set(base) >= set(range(1, 5))
    for c in net.commits[1:]:
        for h in range(1, 5):
            assert c.get(h) == base[h]


def test_threaded_kill_and_reset_height_rejoin():
    # A replica's thread is stopped mid-run (its inbox goes dark, so the
    # broadcast fan-out marks it offline to keep senders unblocked), the
    # survivors — still a quorum — keep committing, then the replica's
    # thread restarts and rejoins via the reset_height resync: it must
    # catch up and commit every height from the rejoin point to the new
    # target, with network-wide safety intact.
    victim = 2
    net = ThreadedNetwork(n=4, target_height=3, timeout=0.3)
    victim_stop = threading.Event()
    vthread = threading.Thread(
        target=net.replicas[victim].run, args=(victim_stop,), daemon=True
    )
    vthread.start()
    threads = []
    for i, r in enumerate(net.replicas):
        if i != victim:
            t = threading.Thread(target=r.run, args=(net.stop,), daemon=True)
            t.start()
            threads.append(t)

    # Phase 1: everyone runs; wait for the victim's first commits, then
    # kill its thread. Marking it offline FIRST keeps broadcasters from
    # blocking on its inbox once nothing drains it.
    assert net.done[victim].wait(60.0), "victim never reached phase-1 target"
    net.offline.add(victim)
    victim_stop.set()
    vthread.join(timeout=5.0)
    killed_at = net.replicas[victim].current_height()

    # Phase 2: survivors alone must keep committing (3 of 4 is a quorum).
    net.target = 6
    for ev in net.done:
        ev.clear()
    deadline = time.monotonic() + 60.0
    for i in range(net.n):
        if i == victim:
            continue
        assert net.done[i].wait(max(0.0, deadline - time.monotonic())), (
            f"survivor {i} stalled at "
            f"{net.replicas[i].current_height()} after the kill"
        )

    # Phase 3: restart the victim's thread and resync it via reset_height.
    # The resync targets a height the survivors haven't reached yet (a
    # margin above their last commit): a rejoiner must buffer that
    # height's traffic from the start — resetting to a height whose
    # round-0 messages already flew past would leave it waiting for votes
    # nobody will resend.
    net.target = 12
    for ev in net.done:
        ev.clear()
    net.offline.discard(victim)
    net_height = max(max(c) for c in net.commits if c) + 3
    victim_stop = threading.Event()
    vthread = threading.Thread(
        target=net.replicas[victim].run, args=(victim_stop,), daemon=True
    )
    vthread.start()
    net.replicas[victim].reset_height(net_height)
    deadline = time.monotonic() + 120.0
    for i in range(net.n):
        assert net.done[i].wait(max(0.0, deadline - time.monotonic())), (
            f"replica {i} stalled at {net.replicas[i].current_height()} "
            "after the rejoin"
        )
    net.stop.set()
    victim_stop.set()
    for t in threads:
        t.join(timeout=5.0)
    vthread.join(timeout=5.0)
    net.assert_safety()
    revived = net.commits[victim]
    assert killed_at < net_height
    for h in range(net_height, 13):
        assert h in revived, f"revived replica missing height {h}"


def test_threaded_offline_proposer_advances_via_real_timeouts():
    # Replica 3 never runs; heights whose round-0 proposer is 3 must
    # progress through a real LinearTimer propose-timeout into round 1.
    net = ThreadedNetwork(n=4, target_height=4, timeout=0.15, offline={3})
    assert net.run(budget_s=60.0), (
        "offline-proposer network stalled: heights="
    ) + str([r.current_height() for r in net.replicas])
    net.assert_safety()
    for i in range(3):
        assert set(net.commits[i]) >= set(range(1, 5))
    assert not net.commits[3]
