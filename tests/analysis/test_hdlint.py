"""hdlint engine + rule specs, driven over the fixture corpus.

The fixtures under ``fixtures/`` are the rule-by-rule contract: every
line commented BAD must be flagged, every line commented GOOD must not.
The repo itself is the other half of the contract: a default strict run
over the installed package must be clean (the CI gate).
"""

import os
import textwrap

import pytest

from hyperdrive_tpu.analysis.__main__ import main
from hyperdrive_tpu.analysis.engine import FileContext, lint_paths
from hyperdrive_tpu.analysis.rules import ALL_RULES, default_rules

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def run_on(path, rules=None, strict=False):
    findings, errors = lint_paths(
        [path], rules if rules is not None else default_rules(), strict=strict
    )
    assert not errors, errors
    return findings


def lines_of(findings, rule):
    return sorted({f.line for f in findings if f.rule == rule})


# ------------------------------------------------------------ fixture corpus


def test_hd001_fixture_flags_every_bad_sync_shape():
    findings = run_on(os.path.join(FIXTURES, "hd001_host_sync.py"))
    assert {f.rule for f in findings} == {"HD001"}
    # .item, block_until_ready, np.asarray(self...), np.asarray(jnp...),
    # bool(self-method), per-element cast — and nothing on the GOOD lines.
    assert len(findings) == 6
    src = open(os.path.join(FIXTURES, "hd001_host_sync.py")).read()
    bad_lines = {
        i + 1 for i, text in enumerate(src.splitlines()) if "# BAD" in text
    }
    assert set(lines_of(findings, "HD001")) == bad_lines


def test_hd002_fixture_flags_retrace_hazards_not_cached_factories():
    findings = run_on(os.path.join(FIXTURES, "hd002_retrace.py"))
    assert {f.rule for f in findings} == {"HD002"}
    msgs = " | ".join(f.message for f in findings)
    assert "no compile cache" in msgs
    assert "references 'self'" in msgs
    assert "mutable default" in msgs
    assert "branch on a traced value" in msgs
    assert len(findings) == 4


def test_hd003_fixture_flags_set_iteration_not_sorted_or_membership():
    findings = run_on(os.path.join(FIXTURES, "hd003_nondet.py"))
    assert {f.rule for f in findings} == {"HD003"}
    assert len(findings) == 4


def test_hd004_fixture_flags_wide_literals_without_dtype_pin():
    findings = run_on(os.path.join(FIXTURES, "hd004_dtype.py"))
    assert {f.rule for f in findings} == {"HD004"}
    assert len(findings) == 3


def test_hd005_fixture_flags_dynamic_names_not_table_lookups():
    path = os.path.join(FIXTURES, "hd005_metric_names.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD005"}
    # f-string count, concat observe, .format emit, uppercase literal,
    # f-string emit — and none of the GOOD lookup/literal/IfExp forms.
    assert len(findings) == 5
    src = open(path).read()
    bad_lines = {
        i + 1 for i, text in enumerate(src.splitlines()) if "# BAD" in text
    }
    assert set(lines_of(findings, "HD005")) == bad_lines
    msgs = " | ".join(f.message for f in findings)
    assert "f-string" in msgs
    assert "concatenated" in msgs
    assert "not lowercase dotted" in msgs


def test_hd005_taxonomy_fixture_flags_closed_family_forks():
    path = os.path.join(FIXTURES, "hd005_taxonomy.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD005"}
    # One unknown name per closed family (sched.launch.*,
    # verify.occupancy.*, metrics.*, bls.*, tenant.drain.*, service.*,
    # exec.*, merkle.*, proof.*, campaign.*, plus an exec.spec.*
    # speculation fork and an admission.reputation.* fork) — and none
    # of the GOOD members, open-family literals, or non-emit methods.
    assert len(findings) == 12
    src = open(path).read()
    bad_lines = {
        i + 1 for i, text in enumerate(src.splitlines()) if "# BAD" in text
    }
    assert set(lines_of(findings, "HD005")) == bad_lines
    assert all("EVENT_KINDS" in f.message for f in findings)


def test_hd005_taxonomy_tracks_recorder_event_kinds():
    # The closed families validated by the lint must actually exist in
    # the taxonomy, so the rule and the recorder cannot drift apart.
    from hyperdrive_tpu.analysis.rules import MetricNameRule
    from hyperdrive_tpu.obs.recorder import EVENT_KINDS

    for prefix in MetricNameRule._CLOSED_PREFIXES:
        assert any(k.startswith(prefix) for k in EVENT_KINDS), prefix


def test_hd006_fixture_flags_blocking_fetches_not_drain_points():
    path = os.path.join(FIXTURES, "hd006_async_fetch.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD006"}
    # submit-then-block, eager mask fetch, marker-scoped block — and
    # neither the callback idiom nor the @drain_point body.
    assert len(findings) == 3
    src = open(path).read()
    bad_lines = {
        i + 1 for i, text in enumerate(src.splitlines()) if "# BAD" in text
    }
    assert set(lines_of(findings, "HD006")) == bad_lines
    assert all("drain_point" in f.message for f in findings)


def test_async_scope_marker_extends_hd006_beyond_devsched(tmp_path):
    src = textwrap.dedent(
        """
        from hyperdrive_tpu.analysis.annotations import (
            async_scope, device_fetch,
        )

        @async_scope
        def pipelined(pending):
            return device_fetch(pending.mask())

        def sequential(pending):
            return device_fetch(pending.mask())
        """
    )
    p = tmp_path / "elsewhere.py"
    p.write_text(src)
    findings = run_on(str(p))
    assert len(findings) == 1  # only the @async_scope body is audited
    assert findings[0].rule == "HD006"


def test_suppressed_fixture_is_clean_even_in_strict():
    path = os.path.join(FIXTURES, "suppressed_clean.py")
    assert run_on(path) == []
    assert run_on(path, strict=True) == []


def test_reasonless_suppression_passes_default_fails_strict():
    path = os.path.join(FIXTURES, "suppressed_reasonless.py")
    assert run_on(path) == []
    strict = run_on(path, strict=True)
    assert [f.rule for f in strict] == ["HD000"]


# ------------------------------------------------------------- repo is clean


def test_repo_passes_strict():
    """The acceptance gate CI runs: the installed package lints clean."""
    assert main(["--strict"]) == 0


# ---------------------------------------------------------------- CLI shape


def test_cli_exit_codes_on_fixture_corpus():
    assert main([FIXTURES]) == 1
    assert main([os.path.join(FIXTURES, "suppressed_clean.py")]) == 0
    assert main(["--list-rules"]) == 0
    assert main(["--rules", "HD999", FIXTURES]) == 2


def test_cli_rule_selection_limits_findings(capsys):
    assert main(["--rules", "HD003", FIXTURES]) == 1
    out = capsys.readouterr().out
    assert "HD003" in out
    assert "HD001" not in out


# -------------------------------------------------------- scopes + hot_path


def test_hot_path_decorator_extends_hd001_beyond_scoped_files(tmp_path):
    src = textwrap.dedent(
        """
        from hyperdrive_tpu.analysis.annotations import hot_path

        @hot_path
        def settle(x):
            return x.item()

        def cold(x):
            return x.item()
        """
    )
    p = tmp_path / "elsewhere.py"
    p.write_text(src)
    findings = run_on(str(p))
    assert len(findings) == 1  # only the @hot_path body is audited
    assert findings[0].rule == "HD001"


def test_unscoped_file_is_exempt_from_path_scoped_rules(tmp_path):
    p = tmp_path / "free.py"
    p.write_text("for x in {1, 2, 3}:\n    print(x)\n")
    assert run_on(str(p)) == []


def test_scope_pragma_opts_a_file_in(tmp_path):
    p = tmp_path / "opted.py"
    p.write_text(
        "# hdlint: scope=digest\nfor x in {1, 2, 3}:\n    print(x)\n"
    )
    findings = run_on(str(p))
    assert [f.rule for f in findings] == ["HD003"]


def test_device_fetch_subtree_is_exempt(tmp_path):
    p = tmp_path / "fetchy.py"
    p.write_text(
        "# hdlint: scope=hot\n"
        "from hyperdrive_tpu.analysis.annotations import device_fetch\n"
        "def f(pending):\n"
        "    return [bool(b) for b in device_fetch(pending.mask())]\n"
    )
    assert run_on(str(p)) == []


def test_suppression_on_preceding_line_covers_next_line():
    ctx = FileContext(
        "x.py",
        "# hdlint: scope=digest\n"
        "# hdlint: disable=HD003 replay order fixed upstream\n"
        "out = [x for x in {1, 2}]\n",
    )
    findings = []
    for rule in default_rules():
        if hasattr(rule, "check"):
            findings.extend(rule.check(ctx))
    assert findings, "sanity: the set iteration is flagged pre-suppression"
    assert all(ctx.suppressed(f) for f in findings)


def test_rule_catalog_is_complete():
    assert set(ALL_RULES) == {
        "HD001", "HD002", "HD003", "HD004", "HD005", "HD006",
        "HD007", "HD008", "HD009", "HD010",
    }
    for cls in ALL_RULES.values():
        assert cls.summary and cls.name


@pytest.mark.parametrize("snippet,expect", [
    # jit stored on self in __init__: a per-instance compile cache
    ("import jax\nclass A:\n    def __init__(self):\n"
     "        self._fn = jax.jit(lambda v: v)\n", 0),
    # jit returned from a factory: the caller owns the lifetime
    ("import jax\ndef make():\n    return jax.jit(lambda v: v)\n", 0),
    # jit called inline per invocation: the actual hazard
    ("import jax\ndef f(x):\n    return jax.jit(lambda v: v)(x)\n", 1),
])
def test_hd002_cache_exemptions(tmp_path, snippet, expect):
    p = tmp_path / "jits.py"
    p.write_text(snippet)
    assert len(run_on(str(p))) == expect


# ------------------------------------------------------- wire rules (HD007+)


def _bad_lines(path):
    src = open(path).read()
    return {
        i + 1 for i, text in enumerate(src.splitlines()) if "# BAD" in text
    }


def test_hd007_fixture_flags_raw_wire_bytes_at_sinks():
    path = os.path.join(FIXTURES, "hd007_wire_taint.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD007"}
    # socket->update, entry->sha256, entry->commit, digest-scope store —
    # and neither the Reader/maybe_wire_reader launders nor the waiver.
    assert len(findings) == 4
    assert set(lines_of(findings, "HD007")) == _bad_lines(path)
    msgs = " | ".join(f.message for f in findings)
    assert "registered" in msgs
    assert "digest-scope state" in msgs


def test_hd008_fixture_flags_unbounded_wire_lengths():
    path = os.path.join(FIXTURES, "hd008_wire_bounds.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD008"}
    # bytearray(n), b"\x00"*n, range(n) without reader consumption,
    # whole-buffer from_bytes — and none of the guarded/budgeted forms.
    assert len(findings) == 4
    assert set(lines_of(findings, "HD008")) == _bad_lines(path)
    msgs = " | ".join(f.message for f in findings)
    assert "bounds check" in msgs
    assert "bigint" in msgs


def test_hd009_fixture_flags_registry_gaps():
    path = os.path.join(FIXTURES, "hd009_codec_pairs.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD009"}
    # unregistered encode_ function, decoder tag with no encoder,
    # unresolvable max_bytes, unregistered marshal/unmarshal class —
    # and neither the paired pair, the registered class, nor the waiver.
    assert len(findings) == 4
    assert set(lines_of(findings, "HD009")) == _bad_lines(path)
    msgs = " | ".join(f.message for f in findings)
    assert "not registered" in msgs
    assert "no registered encoder" in msgs
    assert "compile-time-constant max_bytes" in msgs


def test_hd010_fixture_flags_undispatched_and_silent_tags():
    path = os.path.join(FIXTURES, "hd010_tag_dispatch.py")
    findings = run_on(path)
    assert {f.rule for f in findings} == {"HD010"}
    # TAG_GONE never compared; Frames.* dispatched but never raising.
    assert len(findings) == 2
    assert set(lines_of(findings, "HD010")) == _bad_lines(path)
    msgs = " | ".join(f.message for f in findings)
    assert "never compared" in msgs
    assert "fail" in msgs


def test_hd010_ignores_modules_without_codecs(tmp_path):
    # The same undispatched tag in a codec-free module (a device/tx
    # kind table, not a wire namespace) is out of HD010's scope.
    p = tmp_path / "kinds.py"
    p.write_text("KIND_A = 1\nKIND_B = 2\nKIND_DEAD = 3\n")
    assert run_on(str(p)) == []


def test_reasonless_wire_waiver_fails_strict(tmp_path):
    src = textwrap.dedent(
        """
        from hyperdrive_tpu.analysis.annotations import wire_entry

        @wire_entry
        def parse(frame):
            from hyperdrive_tpu.codec import Reader
            r = Reader(frame)
            n = r.u32()
            return bytearray(n)  # hdlint: disable=HD008
        """
    )
    p = tmp_path / "waived.py"
    p.write_text(src)
    assert run_on(str(p)) == []  # waived in the default run
    strict = run_on(str(p), strict=True)
    assert [f.rule for f in strict] == ["HD000"]  # reasonless = hygiene


def test_wire_taint_flows_through_helper_calls(tmp_path):
    # Interprocedural propagation: bytes received in one function and
    # hashed in another are still flagged at the sink.
    src = textwrap.dedent(
        """
        from hashlib import sha256

        def absorb(body):
            return sha256(body)

        def pump(sock):
            data = sock.recv(4096)
            return absorb(data)
        """
    )
    p = tmp_path / "flows.py"
    p.write_text(src)
    findings = run_on(str(p))
    assert [f.rule for f in findings] == ["HD007"]
    assert "sha256" in findings[0].message


def test_cli_wire_report_lists_every_registered_tag(capsys):
    from hyperdrive_tpu.analysis.annotations import (
        WIRE_BUDGETS,
        WIRE_CODECS,
    )

    # Force the registries that populate on module import.
    import hyperdrive_tpu.harness.sim  # noqa: F401
    import hyperdrive_tpu.overlay.runtime  # noqa: F401
    import hyperdrive_tpu.transport  # noqa: F401

    assert main(["--wire-report"]) == 0
    out = capsys.readouterr().out
    for tag in set(WIRE_CODECS) | set(WIRE_BUDGETS):
        assert tag in out, f"--wire-report is missing {tag}"
    assert "MAX_BYTES" in out
