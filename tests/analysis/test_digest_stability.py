"""Digest-stability regression for the sim.py set-union ordering fix.

``assert_safety`` walks the union of every replica's committed heights;
before the fix that union was iterated in raw ``set`` order, which is
hash-seed dependent, so the first reported violation — and anything
digesting the walk — drifted between interpreter invocations. The walk
is now sorted, and ``commit_digest()`` is the regression handle: two
runs that committed the same chain must produce the same hex digest no
matter how the commit maps were built up.
"""

from hyperdrive_tpu.harness import Simulation
from hyperdrive_tpu.harness.sim import SimulationResult


def result_with(commits):
    return SimulationResult(
        completed=True,
        steps=0,
        virtual_time=0.0,
        heights=[max(c) for c in commits],
        commits=commits,
        record=None,
        alive=[True] * len(commits),
    )


def chain(heights, order):
    """One replica's commit map with a chosen dict insertion order."""
    vals = {h: bytes([h % 251]) * 32 for h in heights}
    return {h: vals[h] for h in order}


def test_digest_ignores_commit_map_insertion_order():
    heights = list(range(1, 40))
    forward = result_with([chain(heights, heights)] * 3)
    backward = result_with([chain(heights, heights[::-1])] * 3)
    shuffled = result_with(
        [chain(heights, sorted(heights, key=lambda h: (h * 7919) % 101))] * 3
    )
    assert forward.commit_digest() == backward.commit_digest()
    assert forward.commit_digest() == shuffled.commit_digest()


def test_digest_merges_partial_overlapping_maps():
    heights = list(range(1, 21))
    full = result_with([chain(heights, heights)])
    # Replicas that each saw only a slice of the chain still merge to the
    # same canonical digest — coverage, not replica count, is what's hashed.
    halves = result_with(
        [chain(heights[:12], heights[:12]), chain(heights[8:], heights[8:])]
    )
    assert full.commit_digest() == halves.commit_digest()


def test_digest_detects_value_tamper():
    heights = list(range(1, 10))
    honest = result_with([chain(heights, heights)])
    tampered_map = chain(heights, heights)
    tampered_map[5] = bytes([0xEE]) * 32
    tampered = result_with([tampered_map])
    assert honest.commit_digest() != tampered.commit_digest()


def test_digest_distinguishes_adjacent_heights():
    # The length-prefixed encoding must not let (h, v) pairs alias across
    # boundaries: same byte soup, different framing.
    a = result_with([{1: b"\x01" * 32, 2: b"\x02" * 32}])
    b = result_with([{1: b"\x02" * 32, 2: b"\x01" * 32}])
    assert a.commit_digest() != b.commit_digest()


def test_identical_seeds_produce_identical_digests():
    a = Simulation(n=4, target_height=3, seed=91).run()
    b = Simulation(n=4, target_height=3, seed=91).run()
    assert a.completed and b.completed
    assert a.commit_digest() == b.commit_digest()


def test_identical_seeds_produce_identical_event_journals():
    """The flight-recorder analogue of the commit-digest spec: the whole
    observed event stream — timestamps (VirtualClock), causality keys,
    ring bookkeeping — must be byte-identical across fixed-seed runs.
    Any hash-order iteration or wall-clock leak in an emit site lands
    here as a digest mismatch."""
    sims = [
        Simulation(
            n=4, target_height=3, seed=91, delivery_cost=0.001, observe=True
        )
        for _ in range(2)
    ]
    results = [s.run() for s in sims]
    assert all(r.completed for r in results)
    a, b = sims
    assert len(a.obs) > 0
    assert a.obs.digest() == b.obs.digest()
    assert a.obs.journal() == b.obs.journal()


def test_observed_run_commits_match_unobserved_run():
    # Recording must be a pure tap: enabling it cannot perturb the
    # consensus outcome of the same seeded scenario.
    plain = Simulation(n=4, target_height=3, seed=91).run()
    observed = Simulation(n=4, target_height=3, seed=91, observe=True).run()
    assert plain.commit_digest() == observed.commit_digest()
