# hdlint: scope=async
"""HD006 fixture: blocking fetches inside a devsched async scope."""

from hyperdrive_tpu.analysis.annotations import (
    async_scope,
    device_fetch,
    drain_point,
)


class AsyncFlusher:
    def __init__(self, queue, launcher):
        self.queue = queue
        self.launcher = launcher

    def submit_then_block(self, items):
        fut = self.queue.submit(self.launcher, items)
        return device_fetch(fut)  # BAD: blocks mid-pipeline

    def eager_mask(self, pending):
        return [bool(b) for b in device_fetch(pending.mask())]  # BAD

    def submit_with_callback(self, items, settle):
        # GOOD: the async idiom — the mask arrives resolved at drain
        fut = self.queue.submit(self.launcher, items)
        fut.add_done_callback(settle)
        return fut

    @drain_point
    def drain_and_read(self, pending):
        # GOOD: a declared drain point is where blocking belongs
        return device_fetch(pending.mask())


@async_scope
def marker_scoped_block(queue, launcher, items):
    fut = queue.submit(launcher, items)
    return device_fetch(fut)  # BAD: marker scope, same discipline
