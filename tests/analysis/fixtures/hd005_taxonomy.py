"""HD005 fixture: closed-family emit literals must be in EVENT_KINDS.

Well-formed lowercase dotted names that sit under the closed event
families (sched.launch.*, verify.occupancy.*, metrics.*, bls.*,
tenant.drain.*, service.*, exec.*, merkle.*, proof.*) but are not
members of the recorder taxonomy
are silent forks — the grep-based journal test only audits files it
covers, the lint covers the rest.
"""


class Pipeline:
    def __init__(self, obs, recorder):
        self.obs = obs
        self.recorder = recorder

    def bad_unknown_launch_kind(self, lid):
        self.obs.emit("sched.launch.finish", -2, -1, -1, lid)  # BAD: fork

    def bad_unknown_occupancy(self, pct):
        self.obs.emit("verify.occupancy.ratio", -1, -1, -1, pct)  # BAD: fork

    def bad_unknown_metrics(self):
        self.recorder.emit("metrics.flush", -1, -1, -1, 0)  # BAD: fork

    def bad_unknown_bls(self, h):
        self.obs.emit("bls.cert.minted", -1, h, -1, 0)  # BAD: fork

    def bad_unknown_drain(self, n):
        self.obs.emit("tenant.drain.skipped", -1, -1, -1, n)  # BAD: fork

    def bad_unknown_service(self, t):
        self.obs.emit("service.remote.ack", -1, -1, -1, t)  # BAD: fork

    def bad_unknown_exec(self, h):
        self.obs.emit("exec.applied", -1, h, -1, 0)  # BAD: fork

    def bad_unknown_spec(self, h):
        self.obs.emit("exec.spec.commit", -1, h, -1, 0)  # BAD: fork

    def bad_unknown_merkle(self, h):
        self.obs.emit("merkle.rebuild", -1, h, -1, 0)  # BAD: fork

    def bad_unknown_proof(self, t):
        self.obs.emit("proof.refused", -1, -1, -1, t)  # BAD: fork

    def bad_unknown_campaign(self, w):
        self.obs.emit("campaign.started", -1, -1, -1, w)  # BAD: fork

    def bad_unknown_reputation(self, p):
        self.obs.emit("admission.reputation.reset", -1, -1, -1, p)  # BAD: fork

    def good_taxonomy_members(self, lid, pct):
        self.obs.emit("sched.launch.begin", -2, -1, -1, lid)
        self.obs.emit("verify.occupancy.pct", -1, -1, -1, pct)
        self.obs.emit("metrics.snapshot", -1, -1, -1, 0)
        self.obs.emit("bls.cert.agg", -1, -1, -1, 0)
        self.obs.emit("bls.partial.reject", -1, -1, -1, 0)
        self.obs.emit("tenant.drain.deferred", -1, -1, -1, 0)
        self.obs.emit("service.remote.resolve", -1, -1, -1, 0)
        self.obs.emit("exec.apply", -1, -1, -1, 0)
        self.obs.emit("exec.root", -1, -1, -1, 0)
        self.obs.emit("exec.stake", -1, -1, -1, 0)
        self.obs.emit("exec.spec.speculate", -1, -1, -1, 0)
        self.obs.emit("exec.spec.confirm", -1, -1, -1, 0)
        self.obs.emit("exec.spec.rollback", -1, -1, -1, 0)
        self.obs.emit("merkle.root", -1, -1, -1, 0)
        self.obs.emit("merkle.update", -1, -1, -1, 0)
        self.obs.emit("proof.serve", -1, -1, -1, 0)
        self.obs.emit("proof.shed", -1, -1, -1, 0)
        self.obs.emit("campaign.family", -1, -1, -1, 0)
        self.obs.emit("campaign.wave", -1, -1, -1, 0)
        self.obs.emit("admission.reputation.charge", -1, -1, -1, 0)
        self.obs.emit("admission.reputation.demote", -1, -1, -1, 0)

    def good_open_family(self):
        # Families outside the closed prefixes stay grep-audited only:
        # a conforming literal is enough.
        self.obs.emit("commit", 5, 0)

    def good_non_emit_methods(self, v):
        # count/observe feed the tracer registry, not the journal; the
        # closed-taxonomy check is emit-only.
        self.tracer = None
        self.obs.count("sched.launch.custom.counter", 1)
        self.obs.observe("metrics.custom.latency", v)
