# hdlint: scope=hot,digest
"""Suppression fixture: every violation is waived with a reason, so a
default run reports nothing and --strict stays clean too."""


def annotated_sync(x):
    return x.item()  # hdlint: disable=HD001 one scalar per commit, measured in BENCH.md


def annotated_union(maps):
    # hdlint: disable=HD003 order feeds a set, not a digest
    return [h for h in set().union(*maps)]
