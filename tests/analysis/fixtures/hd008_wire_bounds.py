"""HD008 fixture: wire-derived lengths must be bounds-checked before
they size an allocation. BAD lines allocate straight off a peer int;
GOOD lines guard first, consume the reader inside the loop, or slice a
constant width."""

from hyperdrive_tpu.analysis.annotations import wire_entry
from hyperdrive_tpu.codec import Reader

_CAP = 4096


@wire_entry
def parse_header(frame):
    r = Reader(frame)
    n = r.u32()
    buf = bytearray(n)  # BAD: peer-sized allocation, no check
    pad = b"\x00" * n  # BAD: peer-sized sequence repeat
    for _ in range(n):  # BAD: loop never consumes the reader
        buf.append(0)
    return buf, pad


@wire_entry
def parse_bigint(frame):
    big = int.from_bytes(frame, "little")  # BAD: whole-buffer bigint
    lo = int.from_bytes(frame[0:8], "little")  # GOOD: constant width
    return big, lo


@wire_entry
def parse_guarded(frame):
    r = Reader(frame)
    m = r.u32()
    if m > _CAP:
        raise ValueError("row count over cap")
    rows = bytearray(m)  # GOOD: m was compared against the cap
    k = min(r.u32(), _CAP)  # GOOD: min() clamps the width
    return rows, bytes(k)


@wire_entry
def parse_budgeted(frame):
    r = Reader(frame)
    count = r.u32()
    return [r.u64() for _ in range(count)]  # GOOD: loop drains r


@wire_entry
def parse_waived(frame):
    r = Reader(frame)
    n = r.u32()
    # hdlint: disable=HD008 trusted intra-host pipe, capped by sender
    return bytearray(n)
