# hdlint: scope=hot
"""Suppression-hygiene fixture: the waiver has no reason, so a default
run is clean but --strict reports HD000."""


def waived_without_reason(x):
    return x.item()  # hdlint: disable=HD001
