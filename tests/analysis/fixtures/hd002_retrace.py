"""HD002 fixture: jit retrace / recompile hazards."""

import functools
from functools import partial

import jax
import jax.numpy as jnp


def recompiles_per_call(x):
    fn = jax.jit(lambda v: v * 2)  # BAD: fresh executable every call
    return fn(x)


@functools.lru_cache(maxsize=None)
def cached_factory(n):
    return jax.jit(lambda v: v * n)  # GOOD: factory is memoized


_CACHE: dict = {}


def dict_cached_factory(k):
    fn = _CACHE.get(k)
    if fn is None:
        fn = _CACHE[k] = jax.jit(lambda v: v + k)  # GOOD: explicit cache
    return fn


class Kernelized:
    def __init__(self):
        self.scale = 2.0
        self._fn = jax.jit(self._impl)  # GOOD: per-instance cache

    def _impl(self, v):
        return v * 2

    @jax.jit
    def bad_method(self, v):
        return v * self.scale  # BAD: jitted body closes over self


@partial(jax.jit, static_argnames=("opts",))
def bad_static_default(v, opts=[]):  # BAD: mutable static default
    return v


@jax.jit
def bad_branch(x, n):
    if x > 0:  # BAD: python branch on a traced value
        return x * n
    return x


@jax.jit
def good_branch(x):
    pad = x.shape[0] - 1
    if pad:  # GOOD: shape-derived, static under trace
        x = jnp.pad(x, (0, pad))
    return x
