"""HD010 fixture: in a codec-bearing module, every TAG_*/KIND_* frame
constant is dispatched somewhere, and some dispatcher rejects unknown
tags with a raise. BAD: a tag nobody compares, and a class namespace
whose only dispatcher falls through silently."""

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, Writer

TAG_PING = 1
TAG_PONG = 2
TAG_GONE = 3  # BAD: never compared in any dispatch


class Frames:
    KIND_DATA = 1  # BAD: namespace dispatched below but never raises
    KIND_ACK = 2


@wire_codec(tag="fixture.pingpong", max_bytes=16)
def encode_ping(kind) -> bytes:
    w = Writer()
    w.u8(kind)
    return w.data()


@wire_codec(tag="fixture.pingpong", max_bytes=16)
def decode_ping(payload):
    k = Reader(payload).u8()
    if k == TAG_PING:
        return "ping"
    if k == TAG_PONG:
        return "pong"
    raise ValueError(f"unknown tag {k}")  # GOOD: fail-closed dispatch


def classify(kind) -> int:
    if kind == Frames.KIND_DATA:
        return 0
    if kind == Frames.KIND_ACK:
        return 1
    return -1  # silent fallthrough: the namespace's HD010 violation
