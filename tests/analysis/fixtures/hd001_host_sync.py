# hdlint: scope=hot
"""HD001 fixture: every implicit-sync shape the rule must catch."""

import numpy as np
import jax.numpy as jnp

from hyperdrive_tpu.analysis.annotations import device_fetch


class Flusher:
    def __init__(self, fn):
        self._fn = fn
        self._out = None

    def scalar_item(self, x):
        return x.item()  # BAD: .item() per scalar

    def eager_block(self, x):
        return x.block_until_ready()  # BAD: unannotated sync

    def convert_device(self):
        return np.asarray(self._out)  # BAD: self state fetched bare

    def convert_jnp(self, a, b):
        return np.asarray(jnp.concatenate([a, b]))  # BAD: jnp fetched bare

    def cast_method_result(self):
        return bool(self._fn())  # BAD: cast over a self-method result

    def per_element(self, pending):
        return [bool(b) for b in pending.mask()]  # BAD: scalar-at-a-time

    def annotated(self, pending):
        # GOOD: the one blessed sync point
        return [bool(b) for b in device_fetch(pending.mask())]

    def host_side(self, rows):
        # GOOD: building a host array from host scalars is not a sync
        return np.array([(r, r + 1) for r in rows])
