"""HD009 fixture: every syntactic codec is registered, registrations
resolve literal tag + constant max_bytes, and every tag has both
directions. BAD: an unregistered encode_ function, an unregistered
marshal/unmarshal class, a decoder tag with no encoder, and a
registration whose max_bytes the linter cannot resolve."""

from hyperdrive_tpu.analysis.annotations import wire_codec
from hyperdrive_tpu.codec import Reader, Writer

import config  # noqa: F401 (stand-in for an unresolvable import)


def encode_widget(obj) -> bytes:  # BAD: codec with no registration
    w = Writer()
    w.u32(obj)
    return w.data()


@wire_codec(tag="fixture.orphan", max_bytes=64)
def decode_orphan(payload):  # BAD tag: decoder with no encoder pair
    return Reader(payload).u32()


@wire_codec(tag="fixture.opaque", max_bytes=config.LIMIT)
def encode_opaque(obj) -> bytes:  # BAD: max_bytes is not resolvable
    return bytes([obj])


class Blob:  # BAD: marshal/unmarshal pair with no registration
    def marshal(self, w) -> None:
        w.u32(0)

    def unmarshal(self, r) -> None:
        r.u32()


@wire_codec(tag="fixture.gadget", max_bytes=128)
def encode_gadget(obj) -> bytes:  # GOOD: registered, paired
    w = Writer()
    w.u64(obj)
    return w.data()


@wire_codec(tag="fixture.gadget", max_bytes=128)
def decode_gadget(payload):  # GOOD: registered, paired
    return Reader(payload).u64()


@wire_codec(tag="fixture.record", max_bytes=256)
class Record:  # GOOD: class registration covers both directions
    def marshal(self, w) -> None:
        w.u32(1)

    def unmarshal(self, r) -> None:
        r.u32()


# hdlint: disable=HD009 scratch codec for a doc example, never on a wire
def encode_scratch(obj) -> bytes:
    return bytes(obj)
