"""HD005 fixture: every dynamic-metric-name shape the rule must catch."""

_MSG_METRIC = {"prevote": "replica.msg.prevote"}


class Replica:
    def __init__(self, tracer, obs):
        self.tracer = tracer
        self.obs = obs

    def bad_fstring(self, kind):
        self.tracer.count(f"replica.caught.{kind}")  # BAD: f-string name

    def bad_concat(self, stage):
        self.tracer.observe("sim." + stage, 1.0)  # BAD: concatenated name

    def bad_format(self, kind):
        self.obs.emit("round.{}".format(kind), 1, 0)  # BAD: call result

    def bad_uppercase(self):
        self.tracer.count("Replica.Msg.Prevote")  # BAD: not lowercase dotted

    def bad_fstring_emit(self, why):
        self.obs.emit(f"fetch.{why}", -1, -1)  # BAD: f-string event kind

    def good_literal(self):
        self.tracer.count("replica.msg.prevote")

    def good_single_word(self):
        self.obs.emit("commit", 5, 0)

    def good_table_lookup(self, t):
        self.tracer.count(_MSG_METRIC[t])

    def good_get_lookup(self, t):
        self.tracer.count(_MSG_METRIC.get(t, "replica.msg.other"))

    def good_ifexp(self, fast):
        self.tracer.count("sim.path.fast" if fast else "sim.path.slow")

    def good_name_passthrough(self, name):
        # A bare name is a lookup whose literals live at the definition
        # site; flagging it would outlaw every table-driven emitter.
        self.tracer.observe(name, 0.5)

    def good_unrelated_receiver(self, kind):
        # Not a tracer/obs/recorder: .emit on anything else is out of scope.
        self.bus.emit(f"signal.{kind}")
