# hdlint: scope=digest
"""HD003 fixture: nondeterministic iteration feeding a digest."""


def digest_over_union(maps):
    acc = []
    for h in set().union(*[set(c) for c in maps]):  # BAD: hash order
        acc.append(h)
    return acc


def digest_over_literal():
    return [x for x in {3, 1, 2}]  # BAD: set literal iteration


def digest_over_named_set(items):
    seen = set(items)
    out = b""
    for s in seen:  # BAD: local known to be a set
        out += s
    return out


def digest_over_binop(a, b):
    return [x for x in set(a) | set(b)]  # BAD: set union operator


def digest_sorted(maps):
    out = []
    for h in sorted(set().union(*[set(c) for c in maps])):  # GOOD
        out.append(h)
    return out


def membership_is_fine(seen, x):
    return x in seen and len(seen) > 0  # GOOD: not iteration
