# hdlint: scope=digest
"""HD007 fixture: raw wire bytes must pass a decoder before digest/
commit/state scope. BAD lines feed socket/entry bytes straight to a
sink; GOOD lines launder through Reader/maybe_wire_reader first."""

from hashlib import sha256

from hyperdrive_tpu.analysis.annotations import wire_entry
from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
from hyperdrive_tpu.codec import Reader


def ingest_frame(sock, h):
    payload = sock.recv(4096)
    h.update(payload)  # BAD: raw peer bytes into a running digest


@wire_entry
def handle_frame(frame):
    return sha256(frame)  # BAD: entry bytes hashed with no decode


@wire_entry
def commit_frame(ledger, frame):
    ledger.commit(frame)  # BAD: entry bytes committed with no decode


class Journal:
    def absorb(self, sock):
        body = sock.recv(1024)
        self.pending = body  # BAD: wire bytes stored in digest scope


@wire_entry
def laundered(frame):
    r = Reader(frame)  # GOOD: the laundering boundary
    return sha256(r.raw())


def budgeted(sock):
    body = sock.recv(1024)
    r = maybe_wire_reader("msg.envelope", body)  # GOOD: budget seam
    return r.raw()


@wire_entry
def waived(frame, h):
    # hdlint: disable=HD007 loopback self-frame, hashed for dedup only
    h.update(frame)
    return h
