# hdlint: scope=ops
"""HD004 fixture: dtype-width drift in a jnp kernel."""

import jax.numpy as jnp


def bad_wide_literal(x):
    return jnp.bitwise_and(x, 0xFFFFFFFF00)  # BAD: width rides the x64 flag


def bad_wide_table():
    return jnp.asarray([0x123456789, 0x98765432AB])  # BAD: no dtype pin


def good_pinned_table():
    # GOOD: dtype pins the width, the literal is a documented constant
    return jnp.asarray([0xFFFFFFFF & 0x6A09E667F3BCC908], dtype=jnp.uint32)


def good_narrow(x):
    return x + 0x7FFFFFFF  # GOOD: fits int32
