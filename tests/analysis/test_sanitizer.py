"""Consensus sanitizer (HDS001–HDS004) specs.

The Byzantine-window scenario: a corrupted device tally (a lying
TallyView — the failure class HDS001 exists for) claims a 2f+1 quorum
the host message logs do not hold. The sanitizer recounts every commit
from the logs and must block it with the rule name in the error. The
other invariants get targeted corruption tests of their own, plus
positive controls proving honest runs sail through untouched.
"""

from types import SimpleNamespace

import pytest

from hyperdrive_tpu.analysis.sanitizer import (
    SanitizerError,
    _SanitizedBroadcaster,
    _SanitizedCommitter,
    enabled,
    install,
    maybe_install,
    maybe_tally_check,
)
from hyperdrive_tpu.messages import Precommit, Propose
from hyperdrive_tpu.process import Process
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CommitterCallback,
    MockProposer,
    MockScheduler,
    MockValidator,
)
from hyperdrive_tpu.types import INVALID_ROUND


def sig(i: int) -> bytes:
    return bytes([i]) * 32


WHOAMI = sig(1)
PROPOSER = sig(2)
OTHERS = [sig(3), sig(4), sig(5)]
VALUE = bytes([0xAB]) * 32


def make_proc(sanitize=True):
    rec = SimpleNamespace(commits=[], prevotes=[], precommits=[], proposes=[])
    proc = Process(
        whoami=WHOAMI,
        f=1,
        timer=None,
        scheduler=MockScheduler(PROPOSER),
        proposer=MockProposer(value=VALUE),
        validator=MockValidator(ok=True),
        broadcaster=BroadcasterCallbacks(
            on_propose=rec.proposes.append,
            on_prevote=rec.prevotes.append,
            on_precommit=rec.precommits.append,
        ),
        committer=CommitterCallback(
            on_commit=lambda h, v: (rec.commits.append((h, v)), (0, None))[1]
        ),
        catcher=None,
        height=1,
    )
    if sanitize:
        install(proc)
    return proc, rec


def deliver_valid_proposal(proc):
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=VALUE, sender=PROPOSER))
    assert proc.state.propose_is_valid.get(0), "fixture: proposal must log"


class LyingTallyView:
    """Claims a 2f+1 precommit quorum regardless of what the logs hold —
    the observable behaviour of a corrupted / Byzantine device tally."""

    def __init__(self, height, claimed):
        self.height = height
        self.rep = 0
        self._claimed = claimed

    def prevotes_for(self, rnd, value):
        return None  # decline: cascade falls back to host counters

    def precommits_for(self, rnd, value):
        return self._claimed

    def prevote_total(self, rnd):
        return None

    def precommit_total(self, rnd):
        return self._claimed


# ------------------------------------------------------- HDS001 (2f+1 recount)


def test_byzantine_device_tally_cannot_force_commit():
    proc, rec = make_proc()
    deliver_valid_proposal(proc)
    # One real precommit in the logs; quorum needs 2f+1 = 3.
    proc.precommit(Precommit(height=1, round=0, value=VALUE,
                             sender=OTHERS[0]))

    with pytest.raises(SanitizerError, match="^HDS001") as exc:
        proc.ingest_cascade(({0}, set()), tallies=LyingTallyView(1, 3))
    assert exc.value.rule == "HDS001"
    assert rec.commits == [], "the lying tally must not reach the app"


def test_honest_quorum_commits_through_the_sanitizer():
    proc, rec = make_proc()
    deliver_valid_proposal(proc)
    for s in OTHERS:
        proc.precommit(Precommit(height=1, round=0, value=VALUE, sender=s))
    assert rec.commits == [(1, VALUE)]
    assert proc.state.current_height == 2


# -------------------------------------------------- HDS002 (locked <= current)


def test_corrupted_locked_round_surfaces_with_rule_name():
    proc, rec = make_proc()
    proc.start()
    proc.state.locked_round = 5  # corruption: lock a round never reached
    with pytest.raises(SanitizerError, match="^HDS002") as exc:
        proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                             value=VALUE, sender=PROPOSER))
    assert exc.value.rule == "HDS002"


# ------------------------------------------------- HDS003 (height monotonic)


def test_commit_at_wrong_height_is_blocked():
    proc, rec = make_proc()
    with pytest.raises(SanitizerError, match="^HDS003"):
        proc.committer.commit(99, VALUE)
    assert rec.commits == []


def test_replayed_commit_height_is_blocked():
    proc, rec = make_proc()
    deliver_valid_proposal(proc)
    for s in OTHERS:
        proc.precommit(Precommit(height=1, round=0, value=VALUE, sender=s))
    assert rec.commits == [(1, VALUE)]
    proc.state.current_height = 1  # roll the automaton back behind its commit
    with pytest.raises(SanitizerError, match="^HDS003"):
        proc.committer.commit(1, VALUE)


# ------------------------------------------------- HDS004 (settle-path parity)


def test_device_host_tally_divergence_surfaces_with_rule_name(monkeypatch):
    monkeypatch.setenv("HD_SANITIZE", "1")
    factory = maybe_tally_check()
    assert factory is not None
    view = SimpleNamespace(
        height=1, rep=0,
        prevotes_for=lambda rnd, value: 7,
        precommits_for=lambda rnd, value: None,
        prevote_total=lambda rnd: None,
        precommit_total=lambda rnd: None,
    )
    proc = SimpleNamespace(
        state=SimpleNamespace(count_prevotes_for=lambda rnd, value: 2)
    )
    checked = factory(view, proc)
    with pytest.raises(SanitizerError, match="^HDS004") as exc:
        checked.prevotes_for(0, VALUE)
    assert exc.value.rule == "HDS004"


def test_matching_tallies_pass_the_parity_check(monkeypatch):
    monkeypatch.setenv("HD_SANITIZE", "1")
    factory = maybe_tally_check()
    view = SimpleNamespace(
        height=1, rep=0,
        prevotes_for=lambda rnd, value: 2,
        precommits_for=lambda rnd, value: None,
        prevote_total=lambda rnd: None,
        precommit_total=lambda rnd: None,
    )
    proc = SimpleNamespace(
        state=SimpleNamespace(count_prevotes_for=lambda rnd, value: 2)
    )
    checked = factory(view, proc)
    assert checked.prevotes_for(0, VALUE) == 2
    assert checked.hits == 1


# ----------------------------------------------------------- wiring + toggles


def test_env_toggle_gates_installation(monkeypatch):
    monkeypatch.setenv("HD_SANITIZE", "0")
    assert not enabled()
    proc, _ = make_proc(sanitize=False)
    before = proc.committer
    maybe_install(proc)
    assert proc.committer is before
    assert maybe_tally_check() is None

    monkeypatch.setenv("HD_SANITIZE", "1")
    assert enabled()
    maybe_install(proc)
    assert isinstance(proc.committer, _SanitizedCommitter)
    assert isinstance(proc.broadcaster, _SanitizedBroadcaster)


def test_install_is_idempotent():
    proc, _ = make_proc()
    once = proc.committer
    install(proc)
    assert proc.committer is once


def test_replica_installs_sanitizer_by_default(monkeypatch):
    monkeypatch.setenv("HD_SANITIZE", "1")

    class AppCommitter:
        def commit(self, height, value):
            return 0, None

    replica = Replica(
        opts=ReplicaOptions(),
        whoami=WHOAMI,
        signatories=[WHOAMI, PROPOSER] + OTHERS,
        timer=None,
        proposer=MockProposer(value=VALUE),
        validator=MockValidator(ok=True),
        committer=AppCommitter(),
        catcher=None,
        broadcaster=BroadcasterCallbacks(),
    )
    assert isinstance(replica.proc.committer, _SanitizedCommitter)
    # The sanitizer wraps the replica's tracing committer, which wraps
    # the app's: attribute access falls through the whole chain.
    assert replica.proc.committer.commit is not None


# -------------------------------------------------------- HDS005 wire budget


def test_unregistered_frame_family_raises(monkeypatch):
    from hyperdrive_tpu.analysis.sanitizer import (
        WireBudget,
        maybe_wire_reader,
    )

    monkeypatch.setenv("HD_SANITIZE", "1")
    with pytest.raises(SanitizerError, match="HDS005.*unregistered"):
        WireBudget("no.such.family")
    with pytest.raises(SanitizerError, match="HDS005"):
        maybe_wire_reader("no.such.family", b"\x00")


def test_oversized_payload_is_charged_up_front(monkeypatch):
    from hyperdrive_tpu.analysis.annotations import WIRE_BUDGETS
    from hyperdrive_tpu.analysis.sanitizer import WireBudget

    monkeypatch.setitem(WIRE_BUDGETS, "test.tiny", 8)
    budget = WireBudget("test.tiny")
    with pytest.raises(SanitizerError, match="HDS005.*budget"):
        budget.reader(b"\x00" * 9)  # wider than the family allows
    assert budget.charge(8) == 8
    with pytest.raises(SanitizerError, match="HDS005"):
        budget.charge(9)


def test_budget_violating_decoder_dies_with_rule_name(monkeypatch):
    # The satellite contract: a decoder that reads PAST its family's
    # declared budget raises HDS005; a merely-truncated payload keeps
    # its typed SerdeError (underflow is malformed input, not a
    # doctrine violation).
    from hyperdrive_tpu.analysis.annotations import WIRE_BUDGETS
    from hyperdrive_tpu.analysis.sanitizer import WireBudget
    from hyperdrive_tpu.codec import SerdeError

    monkeypatch.setitem(WIRE_BUDGETS, "test.tiny", 8)

    def greedy_decode(payload):
        r = WireBudget("test.tiny").reader(payload)
        r.u64()
        return r.u8()  # 9th byte: past the family budget

    with pytest.raises(SanitizerError, match="HDS005"):
        greedy_decode(b"\x00" * 8)

    def truncated_decode(payload):
        r = WireBudget("test.tiny").reader(payload)
        return r.u32(), r.u32()

    with pytest.raises(SerdeError):
        truncated_decode(b"\x00" * 2)  # underflow, budget untouched


def test_budget_breach_emits_wire_budget_event(monkeypatch):
    from hyperdrive_tpu.analysis.annotations import WIRE_BUDGETS
    from hyperdrive_tpu.analysis.sanitizer import WireBudget

    monkeypatch.setitem(WIRE_BUDGETS, "test.tiny", 8)
    events = []
    obs = SimpleNamespace(
        emit=lambda kind, node, h, r, detail: events.append((kind, detail))
    )
    with pytest.raises(SanitizerError):
        WireBudget("test.tiny", obs=obs).charge(64)
    assert events == [("wire.budget.exceeded", "test.tiny:64")]


def test_maybe_wire_reader_off_path_is_a_plain_reader(monkeypatch):
    from hyperdrive_tpu.analysis.sanitizer import maybe_wire_reader
    from hyperdrive_tpu.codec import MAX_BYTES, Reader

    monkeypatch.setenv("HD_SANITIZE", "0")
    r = maybe_wire_reader("no.such.family", b"\x01\x02")
    assert type(r) is Reader  # no budget subclass, no registry check
    assert r.rem == MAX_BYTES
    r2 = maybe_wire_reader("no.such.family", b"\x01", rem=7)
    assert r2.rem == 7  # legacy seam budgets survive sanitizer-off


def test_wire_charge_is_a_noop_when_disabled(monkeypatch):
    from hyperdrive_tpu.analysis.sanitizer import wire_charge

    monkeypatch.setenv("HD_SANITIZE", "0")
    assert wire_charge("no.such.family", 1 << 40) == 1 << 40

    monkeypatch.setenv("HD_SANITIZE", "1")
    with pytest.raises(SanitizerError, match="HDS005"):
        wire_charge("no.such.family", 1)


def test_registered_budgets_match_the_annotations(monkeypatch):
    # The runtime resolves the SAME budget the registration declared:
    # the min across a tag's specs (a family is as strict as its
    # tightest registration).
    from hyperdrive_tpu.analysis.annotations import (
        WIRE_CODECS,
        wire_budget_for,
    )
    from hyperdrive_tpu.analysis.sanitizer import WireBudget

    monkeypatch.setenv("HD_SANITIZE", "1")
    import hyperdrive_tpu.messages  # noqa: F401 (registers msg.*)

    for tag, specs in WIRE_CODECS.items():
        assert WireBudget(tag).max_bytes == wire_budget_for(tag)
        assert wire_budget_for(tag) == min(s.max_bytes for s in specs)
