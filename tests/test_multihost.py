"""Multi-host plumbing on the 8-device CPU mesh.

Real DCN behavior needs a pod; what IS testable single-process — and is
the same code the pod runs — is: hybrid-mesh construction produces the
('hr', 'val') topology every consumer expects, window distribution puts
shards where the mesh says, and the sharded verify+tally step computes
identical results on a hybrid-constructed mesh. init_distributed's no-op
path is exercised implicitly (conftest never starts a coordinator).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperdrive_tpu.crypto.keys import KeyRing
from hyperdrive_tpu.ops.tally import pack_values
from hyperdrive_tpu.parallel import (
    global_window_from_local,
    init_distributed,
    make_hybrid_mesh,
    make_mesh,
    replicate_to_all_hosts,
    sharded_verify_tally,
    grid_pack,
)
from jax.sharding import PartitionSpec as P


def test_init_distributed_single_process_is_noop():
    assert init_distributed() == 1
    assert jax.process_count() == 1


def test_hybrid_mesh_shapes_and_axis_names():
    mesh = make_hybrid_mesh(hr_dcn=2, val_ici=4)
    assert mesh.axis_names == ("hr", "val")
    assert mesh.devices.shape == (2, 4)
    # Defaults: single process -> hr collapses to 1, val spans all devices.
    mesh_default = make_hybrid_mesh()
    assert mesh_default.devices.shape == (1, 8)
    with pytest.raises(ValueError):
        make_hybrid_mesh(hr_dcn=3, val_ici=3)


def test_window_distribution_places_shards():
    mesh = make_hybrid_mesh(hr_dcn=2, val_ici=4)
    local = np.arange(2 * 4 * 20, dtype=np.int32).reshape(2, 4, 20)
    (arr,) = global_window_from_local(mesh, (local,))
    assert arr.shape == (2, 4, 20)
    # Each of the 8 devices holds exactly one [1, 1, 20] shard.
    shapes = {s.data.shape for s in arr.addressable_shards}
    assert shapes == {(1, 1, 20)}
    assert len(arr.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(arr), local)


def test_replicate_places_full_copy_everywhere():
    mesh = make_hybrid_mesh(hr_dcn=2, val_ici=4)
    val = np.arange(8, dtype=np.int32)
    arr = replicate_to_all_hosts(mesh, val)
    assert {s.data.shape for s in arr.addressable_shards} == {(8,)}
    np.testing.assert_array_equal(np.asarray(arr), val)


@pytest.mark.requires_multiprocess
def test_sharded_step_on_hybrid_mesh_matches_plain_mesh():
    R, V = 2, 4
    ring = KeyRing.deterministic(V, namespace=b"mh")
    values = [bytes([r + 7]) * 32 for r in range(R)]
    corrupt = {(1, 3)}
    shaped, _ = grid_pack(ring, R, V, values, corrupt=corrupt)
    vote_vals = jnp.asarray(
        np.stack([pack_values([values[r]] * V) for r in range(R)])
    )
    target_vals = jnp.asarray(pack_values(values))
    f = jnp.int32(V // 3)

    results = []
    for mesh in (make_hybrid_mesh(hr_dcn=2, val_ici=4), make_mesh(hr=2, val=4)):
        step = sharded_verify_tally(mesh)
        window = global_window_from_local(mesh, shaped)
        counts, flags, ok = step(*window, vote_vals, target_vals, f)
        results.append(
            (
                np.asarray(ok),
                {k: np.asarray(v) for k, v in counts.items()},
                {k: np.asarray(v) for k, v in flags.items()},
            )
        )

    ok_a, counts_a, flags_a = results[0]
    ok_b, counts_b, flags_b = results[1]
    np.testing.assert_array_equal(ok_a, ok_b)
    for k in counts_a:
        np.testing.assert_array_equal(counts_a[k], counts_b[k])
    for k in flags_a:
        np.testing.assert_array_equal(flags_a[k], flags_b[k])
    # And the expected semantics: the corrupted lane failed, quorum holds.
    assert not ok_a[1, 3]
    assert int(counts_a["matching"][1]) == V - 1


@pytest.mark.requires_multiprocess
def test_two_process_distributed_step_and_consensus():
    # The REAL multi-process branches — jax.distributed rendezvous, hybrid
    # DCN mesh construction, host_local_array_to_global_array,
    # broadcast_one_to_all — executed by two actual processes (2 CPU
    # devices each = a 2x2 pod) driving (1) the sharded verify+tally step
    # and (2) a FULL sharded-grid consensus run: 3 heights committed
    # through a vote grid whose validator axis spans the process boundary
    # (every settle's psum is a cross-process collective), device counts
    # checked equal to host counters, commit maps all-gather-verified
    # identical across processes. Each worker prints MULTIHOST_OK and
    # MULTIHOST_CONSENSUS_OK; any assertion exits nonzero.
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {
        k: v
        for k, v in os.environ.items()
        # The parent's 8-device XLA_FLAGS must not leak into the workers,
        # and PALLAS_AXON_POOL_IPS triggers the container sitecustomize's
        # TPU-plugin registration at interpreter startup — before the
        # worker's jax.distributed.initialize could ever run first.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"
        assert f"MULTIHOST_OK rank={rank} procs=2 devices=4" in out, out
        assert f"MULTIHOST_CONSENSUS_OK rank={rank} heights=3" in out, out


def test_hybrid_mesh_multiprocess_requires_divisible_hr(monkeypatch):
    # Validation fires before any mesh_utils call, so the multi-process
    # branch is testable by pinning the process count: 3 processes
    # cannot tile an 'hr' axis of 2 without splitting a granule.
    from hyperdrive_tpu.parallel import multihost

    monkeypatch.setattr(multihost.jax, "process_count", lambda: 3)
    with pytest.raises(ValueError, match="multiple of the process"):
        make_hybrid_mesh(hr_dcn=2, val_ici=4)


def test_hybrid_mesh_multiprocess_rejects_local_shape_mismatch(monkeypatch):
    # Misconfigured pod: the global average (8 devices / 2 processes)
    # admits a 1x4 per-granule tile, but THIS process only sees 2
    # devices — the local-slab check must fail loudly, not let
    # create_hybrid_device_mesh build a mesh over devices that are not
    # attached here.
    from hyperdrive_tpu.parallel import multihost

    monkeypatch.setattr(multihost.jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost.jax, "local_device_count", lambda: 2)
    with pytest.raises(ValueError, match="attached to this process"):
        make_hybrid_mesh(hr_dcn=2, val_ici=4)


def test_global_window_parity_across_mesh_shapes():
    # The single-process device_put branch must assemble the same global
    # values whatever the (hr, val) factorization — the shape every
    # consumer sees is topology-independent, only placement moves.
    local = np.arange(8 * 8, dtype=np.int32).reshape(8, 8)
    flat = global_window_from_local(make_hybrid_mesh(hr_dcn=1, val_ici=8),
                                    (local,))[0]
    grid = global_window_from_local(make_hybrid_mesh(hr_dcn=2, val_ici=4),
                                    (local,))[0]
    np.testing.assert_array_equal(np.asarray(flat), local)
    np.testing.assert_array_equal(np.asarray(grid), local)
    # Forced-8-device placement really sharded (one row-block per chip).
    assert len(flat.addressable_shards) == 8


def test_global_window_accepts_custom_spec():
    mesh = make_hybrid_mesh(hr_dcn=2, val_ici=4)
    local = np.arange(4 * 8, dtype=np.int32).reshape(4, 8)
    (arr,) = global_window_from_local(mesh, (local,), spec=P(None, "val"))
    # Sharded only over 'val': 4 distinct column shards, replicated on 'hr'.
    assert {s.data.shape for s in arr.addressable_shards} == {(4, 2)}
    np.testing.assert_array_equal(np.asarray(arr), local)
