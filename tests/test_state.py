"""State: defaults, clone isolation, equality semantics, serde, fuzz.

Mirrors process/state_test.go's strategy.
"""

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import Prevote
from hyperdrive_tpu.state import State
from hyperdrive_tpu.testutil import random_state
from hyperdrive_tpu.types import (
    DEFAULT_HEIGHT,
    INVALID_ROUND,
    NIL_VALUE,
    Step,
)


def test_defaults():
    st = State()
    assert st.current_height == DEFAULT_HEIGHT
    assert st.current_round == 0
    assert st.current_step == Step.PROPOSING
    assert st.locked_value == NIL_VALUE
    assert st.locked_round == INVALID_ROUND
    assert st.valid_value == NIL_VALUE
    assert st.valid_round == INVALID_ROUND
    assert not st.propose_logs and not st.prevote_logs and not st.precommit_logs


def test_clone_is_deep(rng):
    st = random_state(rng)
    cl = st.clone()
    assert cl.equal(st)
    # Mutating the clone's logs must not touch the original.
    pv = Prevote(height=1, round=0, value=b"\x05" * 32, sender=b"\x06" * 32)
    cl.prevote_logs.setdefault(0, {})[pv.sender] = pv
    cl.trace_logs.setdefault(0, set()).add(pv.sender)
    assert pv.sender not in st.prevote_logs.get(0, {})
    assert pv.sender not in st.trace_logs.get(0, set())


def test_equality_ignores_logs(rng):
    st = random_state(rng)
    cl = st.clone()
    cl.propose_logs.clear()
    cl.once_flags.clear()
    assert st.equal(cl)
    cl.current_round += 1
    assert not st.equal(cl)


def test_serde_roundtrip(rng):
    for _ in range(50):
        st = random_state(rng)
        w = Writer()
        st.marshal(w)
        back = State.unmarshal(Reader(w.data()))
        assert back.equal(st)
        assert back.propose_logs == st.propose_logs
        assert back.propose_is_valid == st.propose_is_valid
        assert back.prevote_logs == st.prevote_logs
        assert back.precommit_logs == st.precommit_logs
        assert back.once_flags == st.once_flags
        assert back.trace_logs == st.trace_logs


def test_undersized_budget_errors(rng):
    st = random_state(rng)
    w = Writer()
    st.marshal(w)
    data = w.data()
    for rem in (0, 1, len(data) // 2):
        try:
            State.unmarshal(Reader(data, rem=rem))
        except SerdeError:
            continue
        # If it succeeded, the budget must genuinely have covered it.
        assert rem >= len(data)


def test_unmarshal_fuzz_no_crash(rng):
    for _ in range(300):
        blob = rng.randbytes(rng.randint(0, 200))
        try:
            State.unmarshal(Reader(blob))
        except SerdeError:
            pass


def test_reset_for_new_height(rng):
    st = random_state(rng)
    st.reset_for_new_height()
    assert st.locked_value == NIL_VALUE
    assert st.locked_round == INVALID_ROUND
    assert st.valid_value == NIL_VALUE
    assert st.valid_round == INVALID_ROUND
    assert not st.propose_logs
    assert not st.prevote_logs
    assert not st.precommit_logs
    assert not st.once_flags
    assert not st.trace_logs


def test_derived_counts_track_logs(rng):
    from hyperdrive_tpu.messages import Precommit, Prevote

    st = State()
    values = [bytes([i + 1]) * 32 for i in range(3)]
    expect = {}
    first = None
    for i in range(60):
        rnd = rng.randrange(3)
        v = values[rng.randrange(3)]
        sender = bytes([i]) * 32
        msg = Prevote(height=1, round=rnd, value=v, sender=sender)
        if first is None:
            first = msg
        assert st.add_prevote(msg) is None
        expect[(rnd, v)] = expect.get((rnd, v), 0) + 1
    for (rnd, v), n in expect.items():
        assert st.count_prevotes_for(rnd, v) == n
    # Same (sender, round) again: returned, not counted.
    count_before = st.count_prevotes_for(first.round, first.value)
    assert st.add_prevote(first) is first
    assert st.count_prevotes_for(first.round, first.value) == count_before

    # Counts survive a serde round-trip (rebuilt, not serialized).
    w = Writer(rem=1 << 20)
    st.marshal(w)
    back = State.unmarshal(Reader(w.data(), rem=1 << 20))
    assert back.prevote_counts == st.prevote_counts
    assert back.precommit_counts == st.precommit_counts

    # And reset wipes them.
    st.reset_for_new_height()
    assert st.count_prevotes_for(0, values[0]) == 0
    assert not st.prevote_counts

    # Precommit side: same contract.
    pc = Precommit(height=1, round=0, value=values[1], sender=b"\x77" * 32)
    assert st.add_precommit(pc) is None
    assert st.count_precommits_for(0, values[1]) == 1
    assert st.add_precommit(pc) is not None
    assert st.count_precommits_for(0, values[1]) == 1
