"""bench.py's sustained harness, smoke-tested in-suite.

The driver runs bench.py on the real chip at round end, and BENCH.md
config 7 calls the same run_sustained; a harness API breakage would
otherwise surface only there, after the round's work. This smoke runs
the full paired-leg pipeline at miniature scale on the CPU platform
(batch 64 — the bucket every other device test already compiles) and
checks the self-describing record's contract.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_run_sustained_smoke():
    import bench

    out = bench.run_sustained(
        validators=16, rounds=4, iters=1, trials=2, full_wire=False,
        namespace=b"benchtest",
    )
    # The headline leg is the 64 B/lane transfer-floor format.
    assert out["bytes_per_lane"] == 64
    assert out["unique_signatures"] is True
    assert out["sustained_votes_per_s"] > 0
    assert len(out["sustained_trials"]) == 2
    # Paired legs all measured, one ratio per trial.
    assert out["sustained_68_votes_per_s"] > 0
    assert out["sustained_hosthash_votes_per_s"] > 0
    assert out["hosthash_bytes_per_lane"] == 100
    assert len(out["paired_64_over_100_ratios"]) == 2
    assert all(r > 0 for r in out["paired_64_over_100_ratios"])
    # Resident-state accounting: table-shaped table bytes, the dense
    # grid index its own key (4 bytes x batch lanes).
    assert out["resident_index_bytes"] == 4 * 16 * 4
    assert out["table_bytes"] > 0
    assert out["device_only_votes_per_s"] > 0
    # Pack legs report. (No rate ORDERING asserted: at this miniature
    # batch, fixed overheads dominate both pack legs and the comparison
    # is timing noise — the real-scale ordering is a BENCH.md claim,
    # not a unit-test contract.)
    assert out["chal_pack_sigs_per_s"] > 0
    assert out["wire_pack_sigs_per_s"] > 0


def test_run_sustained_rejects_tampered_lane(monkeypatch):
    """The harness must REFUSE to publish a rate over unverified work: a
    batch with one forged signature fails the pipeline's mask check."""
    import bench
    import pytest

    real = bench._build_batches

    def tampered(ring, validators, rounds, iters, namespace):
        batches, tallies, m_rounds = real(
            ring, validators, rounds, iters, namespace
        )
        pub, digest, sig = batches[0][3]
        # Flip the LOW byte of S (S +/- 1): stays < L for any derived
        # signature, so the forgery reaches the device mask check (the
        # RuntimeError path) rather than tripping the packer's s < L
        # prevalid gate, whose failure mode is a different exception.
        batches[0][3] = (
            pub, digest, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        )
        return batches, tallies, m_rounds

    monkeypatch.setattr(bench, "_build_batches", tampered)
    with pytest.raises(RuntimeError):
        bench.run_sustained(
            validators=16, rounds=4, iters=1, trials=1, full_wire=False,
            namespace=b"benchtest2",
        )
