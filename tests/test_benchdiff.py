"""Perf-regression sentinel (obs/benchdiff.py): the CI gate's contract.

Self-vs-self must pass, an injected slowdown on a gated series must
fail with nonzero exit, runner-speed scale factors on ungated absolute
metrics must NOT fail, and noisy series get widened bounds from their
own scatter.
"""

import copy
import json

import pytest

from hyperdrive_tpu.obs.benchdiff import (
    _direction,
    compare,
    main as benchdiff_main,
    render,
)

ARTIFACT = {
    "benchdiff_gate": ["consensus.block_wall_s", "verify.speedup"],
    "consensus": {
        # Per-block wall series: medians compare, one outlier is free.
        "block_wall_s": [0.010, 0.011, 0.010, 0.012, 0.010, 0.011],
        "heights_per_s": 95.0,
    },
    "verify": {"speedup": [3.0, 3.1, 2.9, 3.0], "rows": 4096},
    "meta": {"seed": 7},
}


def test_self_vs_self_passes():
    v = compare(ARTIFACT, copy.deepcopy(ARTIFACT))
    assert not v["failed"]
    assert v["regressions"] == []
    assert v["gates"] == ARTIFACT["benchdiff_gate"]


def test_injected_slowdown_on_gated_series_fails():
    slow = copy.deepcopy(ARTIFACT)
    slow["consensus"]["block_wall_s"] = [
        v * 1.6 for v in slow["consensus"]["block_wall_s"]
    ]
    v = compare(ARTIFACT, slow)
    assert v["failed"]
    [reg] = v["gated_regressions"]
    assert reg["path"] == "consensus.block_wall_s"
    assert reg["series"] and reg["delta"] == pytest.approx(0.6, abs=0.05)
    assert "REGRESSION [GATED]" in render(v)
    assert "FAIL" in render(v)


def test_gated_ratio_drop_fails_in_the_higher_is_better_direction():
    worse = copy.deepcopy(ARTIFACT)
    worse["verify"]["speedup"] = [1.5, 1.6, 1.4, 1.5]
    v = compare(ARTIFACT, worse)
    assert v["failed"]
    assert any(
        e["path"] == "verify.speedup" for e in v["gated_regressions"]
    )
    # A speedup INCREASE is an improvement, never a regression.
    better = copy.deepcopy(ARTIFACT)
    better["verify"]["speedup"] = [6.0, 6.1, 5.9, 6.0]
    v2 = compare(ARTIFACT, better)
    assert not v2["failed"]
    assert any(e["path"] == "verify.speedup" for e in v2["improvements"])


def test_ungated_regression_reports_but_does_not_fail():
    slower = copy.deepcopy(ARTIFACT)
    slower["consensus"]["heights_per_s"] = 40.0
    v = compare(ARTIFACT, slower)
    assert not v["failed"]  # informational: not a nominated gate
    assert any(
        e["path"] == "consensus.heights_per_s" for e in v["regressions"]
    )


def test_noise_bound_widens_with_series_scatter():
    noisy = {
        "benchdiff_gate": ["wall_s"],
        # Median 1.0, MAD 0.3 -> bound 4 * 0.3 = 120%: a 50% median
        # shift is within this series' own run-to-run scatter.
        "wall_s": [0.7, 1.0, 1.3, 0.6, 1.0, 1.4, 1.0],
    }
    shifted = {"benchdiff_gate": ["wall_s"], "wall_s": [1.5] * 7}
    v = compare(noisy, shifted)
    assert not v["failed"]
    # A tight series holds the default threshold instead.
    tight = {"benchdiff_gate": ["wall_s"], "wall_s": [1.0] * 7}
    v2 = compare(tight, {"benchdiff_gate": ["wall_s"], "wall_s": [1.5] * 7})
    assert v2["failed"]


def test_direction_inference():
    assert _direction("consensus.heights_per_s") == 1
    assert _direction("verify.speedup") == 1
    assert _direction("consensus.block_wall_s") == -1
    assert _direction("tenant.latency") == -1
    assert _direction("meta.seed") == 0


def test_unknown_direction_skipped_unless_gated():
    old = {"mystery": 10.0}
    new = {"mystery": 100.0}
    v = compare(old, new)
    assert any(s["path"] == "mystery" for s in v["skipped"])
    v2 = compare(old, new, gates=["mystery"])  # gated: lower-is-better
    assert v2["failed"]


def test_gate_prefix_covers_subtree():
    old = {"consensus": {"commit_wall_s": 1.0, "drop_rate": 0.1}}
    new = {"consensus": {"commit_wall_s": 2.0, "drop_rate": 0.1}}
    v = compare(old, new, gates=["consensus"])
    assert v["failed"]
    assert v["gated_regressions"][0]["path"] == "consensus.commit_wall_s"


def test_shape_mismatch_and_short_series_skip():
    v = compare(
        {"a_wall_s": [1.0, 1.0, 1.0], "b_wall_s": [1.0, 2.0]},
        {"a_wall_s": 1.0, "b_wall_s": [1.0, 2.0]},
    )
    reasons = {s["path"]: s["reason"] for s in v["skipped"]}
    assert reasons["a_wall_s"] == "shape-mismatch"
    assert reasons["b_wall_s"] == "short-series"


def test_zero_baseline_skips_rather_than_divides():
    v = compare({"lat_s": 0.0}, {"lat_s": 0.5}, gates=["lat_s"])
    assert not v["failed"]
    assert any(s["reason"] == "zero-baseline" for s in v["skipped"])
    v2 = compare({"lat_s": 0.0}, {"lat_s": 0.0}, gates=["lat_s"])
    assert not v2["failed"]


def test_cli_exit_codes_and_json(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(ARTIFACT))
    new.write_text(json.dumps(ARTIFACT))
    assert benchdiff_main(str(old), str(new)) == 0
    out = capsys.readouterr().out
    assert "PASS" in out

    slow = copy.deepcopy(ARTIFACT)
    slow["consensus"]["block_wall_s"] = [
        v * 2 for v in slow["consensus"]["block_wall_s"]
    ]
    new.write_text(json.dumps(slow))
    assert benchdiff_main(str(old), str(new)) == 1
    assert "FAIL" in capsys.readouterr().out

    assert benchdiff_main(str(old), str(new), as_json=True) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["failed"] is True


def test_obs_cli_benchdiff_subcommand(tmp_path, capsys):
    from hyperdrive_tpu.obs.__main__ import main as obs_main

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(ARTIFACT))
    new.write_text(json.dumps(ARTIFACT))
    assert obs_main(["benchdiff", str(old), str(new)]) == 0
    capsys.readouterr()
    slow = copy.deepcopy(ARTIFACT)
    slow["verify"]["speedup"] = [1.0, 1.0, 1.0, 1.0]
    new.write_text(json.dumps(slow))
    assert obs_main(["benchdiff", str(old), str(new)]) == 1
