"""Property-based consensus rule specs (hypothesis).

The reference wraps nearly every rule spec in ``testing/quick`` randomization
(process/process_test.go, e.g. 95-105) and dedicates a long negative-case
matrix to the future-round skip rule (process_test.go:3279-3803). This module
is that layer: every L-rule gets randomized positive AND negative specs, the
message interleavings are randomized at the Process level, and the serde
properties run over the edge-case-biased generators from
``hyperdrive_tpu.testutil``.

Conventions: ``f`` ranges over small quorum sizes, sender identities are
distinct 32-byte tags, and assertions are on observable side effects
(broadcasts, timeouts, commits, catches) — the same surface the reference
asserts on.
"""

import random
from types import SimpleNamespace

import pytest

pytest.importorskip(
    "hypothesis",
    reason="property-based specs need hypothesis (not in this image)",
)

from hypothesis import given, settings, strategies as st

from hyperdrive_tpu.codec import Reader, SerdeError, Writer
from hyperdrive_tpu.messages import (
    Precommit,
    Prevote,
    Propose,
    marshal_message,
    unmarshal_message,
)
from hyperdrive_tpu.process import Process
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockScheduler,
    MockValidator,
    TimerCallbacks,
    random_precommit,
    random_prevote,
    random_propose,
    random_state,
)
from hyperdrive_tpu.types import INT64_MAX, INVALID_ROUND, NIL_VALUE, Step

# Shared hypothesis profile: rule properties drive a full automaton per
# example, so keep example counts moderate and disable the wall-clock
# deadline (CI machines vary).
RULES = settings(max_examples=60, deadline=None)
SERDE = settings(max_examples=120, deadline=None)


def sig(i: int) -> bytes:
    return bytes([i]) * 32


def val(i: int) -> bytes:
    return bytes([0xA0 + (i % 0x5F)]) * 32


WHOAMI = sig(1)
PROPOSER = sig(2)


def make_process(whoami=WHOAMI, f=1, proposer_sig=PROPOSER, validator_ok=True,
                 proposer_value=None, height=1):
    rec = SimpleNamespace(
        proposes=[], prevotes=[], precommits=[], commits=[],
        timeout_proposes=[], timeout_prevotes=[], timeout_precommits=[],
        double_proposes=[], double_prevotes=[], double_precommits=[],
        out_of_turns=[],
    )
    proc = Process(
        whoami=whoami,
        f=f,
        timer=TimerCallbacks(
            on_propose=lambda h, r: rec.timeout_proposes.append((h, r)),
            on_prevote=lambda h, r: rec.timeout_prevotes.append((h, r)),
            on_precommit=lambda h, r: rec.timeout_precommits.append((h, r)),
        ),
        scheduler=MockScheduler(proposer_sig),
        proposer=MockProposer(value=proposer_value or val(0)),
        validator=MockValidator(ok=validator_ok),
        broadcaster=BroadcasterCallbacks(
            on_propose=rec.proposes.append,
            on_prevote=rec.prevotes.append,
            on_precommit=rec.precommits.append,
        ),
        committer=CommitterCallback(
            on_commit=lambda h, v: (rec.commits.append((h, v)), (0, None))[1]
        ),
        catcher=CatcherCallbacks(
            on_double_propose=lambda a, b: rec.double_proposes.append((a, b)),
            on_double_prevote=lambda a, b: rec.double_prevotes.append((a, b)),
            on_double_precommit=lambda a, b: rec.double_precommits.append((a, b)),
            on_out_of_turn_propose=rec.out_of_turns.append,
        ),
        height=height,
    )
    return proc, rec


# Strategy helpers -----------------------------------------------------------

fs = st.integers(min_value=1, max_value=3)
seeds = st.integers(min_value=0, max_value=2**32 - 1)
rounds = st.integers(min_value=0, max_value=1 << 20)
good_values = st.integers(min_value=1, max_value=0x5E).map(val)


def senders(n: int, offset: int = 10) -> list[bytes]:
    return [sig(offset + i) for i in range(n)]


def deliver(proc, msgs, order_seed: int) -> list:
    """Deliver msgs in a seed-determined random order; returns the order."""
    order = list(msgs)
    random.Random(order_seed).shuffle(order)
    for m in order:
        if isinstance(m, Propose):
            proc.propose(m)
        elif isinstance(m, Prevote):
            proc.prevote(m)
        else:
            proc.precommit(m)
    return order


# ------------------------------------------------------------ L11 StartRound


@RULES
@given(f=fs, am_proposer=st.booleans())
def test_l11_start_round(f, am_proposer):
    proc, rec = make_process(
        whoami=PROPOSER if am_proposer else WHOAMI, f=f
    )
    proc.start()
    assert proc.current_round == 0
    assert proc.current_step == Step.PROPOSING
    if am_proposer:
        assert [p.value for p in rec.proposes] == [val(0)]
        assert rec.timeout_proposes == []
    else:
        assert rec.proposes == []
        assert rec.timeout_proposes == [(1, 0)]


# ------------------------------------------- L22 prevote upon (valid) propose


@RULES
@given(f=fs, value=good_values, ok=st.booleans())
def test_l22_prevote_tracks_validity(f, value, ok):
    proc, rec = make_process(f=f, validator_ok=ok)
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=value, sender=PROPOSER))
    assert [pv.value for pv in rec.prevotes] == [value if ok else NIL_VALUE]
    assert proc.current_step == Step.PREVOTING


@RULES
@given(f=fs, value=good_values)
def test_l22_negative_out_of_turn_proposer_never_prevoted(f, value):
    proc, rec = make_process(f=f)
    proc.start()
    imposter = sig(9)  # not the scheduled proposer
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=value, sender=imposter))
    assert rec.prevotes == []
    assert [p.sender for p in rec.out_of_turns] == [imposter]


# ---------------------------- L28 prevote upon propose + 2f+1 past prevotes


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_l28_repropose_with_quorum_from_valid_round(f, value, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    vr = 0
    # Jump to round 2 via f+1 future-round messages (L55), then deliver the
    # re-propose with valid_round=vr plus a 2f+1 prevote quorum at vr.
    for s in senders(f + 1, offset=40):
        proc.prevote(Prevote(height=1, round=2, value=value, sender=s))
    assert proc.current_round == 2
    msgs = [Propose(height=1, round=2, valid_round=vr, value=value,
                    sender=PROPOSER)]
    msgs += [Prevote(height=1, round=vr, value=value, sender=s)
             for s in senders(2 * f + 1)]
    deliver(proc, msgs, order_seed)
    assert [pv.value for pv in rec.prevotes if pv.round == 2] == [value]


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_l28_negative_sub_quorum_never_fires(f, value, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    for s in senders(f + 1, offset=40):
        proc.prevote(Prevote(height=1, round=2, value=value, sender=s))
    msgs = [Propose(height=1, round=2, valid_round=0, value=value,
                    sender=PROPOSER)]
    msgs += [Prevote(height=1, round=0, value=value, sender=s)
             for s in senders(2 * f)]  # one short of quorum
    deliver(proc, msgs, order_seed)
    assert [pv for pv in rec.prevotes if pv.round == 2] == []


# --------------------------- L34 prevote timeout upon 2f+1 current prevotes


@RULES
@given(f=fs, order_seed=seeds)
def test_l34_any_quorum_of_prevotes_schedules_timeout(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=val(3), sender=PROPOSER))  # step -> PREVOTING
    who = senders(2 * f + 1)
    # Split the votes so no value reaches 2f+1 (a value quorum would fire
    # L36 first and legitimately leave PREVOTING before L34 checks).
    msgs = [Prevote(height=1, round=0, value=val(3 + (i % 2)), sender=s)
            for i, s in enumerate(who)]
    deliver(proc, msgs, order_seed)
    assert (1, 0) in rec.timeout_prevotes


@RULES
@given(f=fs, order_seed=seeds)
def test_l34_negative_duplicates_do_not_count(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=val(3), sender=PROPOSER))
    # 2f+1 messages but only 2f unique senders (one equivocates).
    who = senders(2 * f)
    msgs = [Prevote(height=1, round=0, value=val(3), sender=s) for s in who]
    msgs.append(Prevote(height=1, round=0, value=val(4), sender=who[0]))
    deliver(proc, msgs, order_seed)
    assert rec.timeout_prevotes == []
    assert len(rec.double_prevotes) == 1


# ------------------------------------- L36 lock + precommit upon 2f+1 match


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_l36_quorum_locks_and_precommits(f, value, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    msgs += [Prevote(height=1, round=0, value=value, sender=s)
             for s in senders(2 * f + 1)]
    deliver(proc, msgs, order_seed)
    assert [pc.value for pc in rec.precommits] == [value]
    assert proc.state.locked_value == value
    assert proc.state.locked_round == 0
    assert proc.state.valid_value == value
    assert proc.current_step == Step.PRECOMMITTING


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_l36_negative_split_vote_never_locks(f, value, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    # 2f+1 prevotes but no single value reaches quorum.
    who = senders(2 * f + 1)
    msgs += [Prevote(height=1, round=0,
                     value=value if i < f else val(0x30 + i), sender=s)
             for i, s in enumerate(who)]
    deliver(proc, msgs, order_seed)
    assert [pc for pc in rec.precommits if pc.value != NIL_VALUE] == []
    assert proc.state.locked_round == INVALID_ROUND


# --------------------------------------- L44 precommit nil upon nil quorum


@RULES
@given(f=fs, order_seed=seeds)
def test_l44_nil_quorum_precommits_nil(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=val(3), sender=PROPOSER))  # step -> PREVOTING
    msgs = [Prevote(height=1, round=0, value=NIL_VALUE, sender=s)
            for s in senders(2 * f + 1)]
    deliver(proc, msgs, order_seed)
    assert [pc.value for pc in rec.precommits] == [NIL_VALUE]
    assert proc.state.locked_round == INVALID_ROUND


@RULES
@given(f=fs, order_seed=seeds)
def test_l44_negative_mixed_nils_below_quorum(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=val(3), sender=PROPOSER))
    msgs = [Prevote(height=1, round=0, value=NIL_VALUE, sender=s)
            for s in senders(2 * f)]  # one short
    deliver(proc, msgs, order_seed)
    assert [pc for pc in rec.precommits if pc.value == NIL_VALUE] == []


# ------------------------------- L47 precommit timeout upon any 2f+1 votes


@RULES
@given(f=fs, order_seed=seeds, mixed=st.booleans())
def test_l47_any_precommit_quorum_schedules_timeout(f, order_seed, mixed):
    proc, rec = make_process(f=f)
    proc.start()
    who = senders(2 * f + 1)
    msgs = [Precommit(height=1, round=0,
                      value=val(5 + (i % 3 if mixed else 0)), sender=s)
            for i, s in enumerate(who)]
    deliver(proc, msgs, order_seed)
    assert (1, 0) in rec.timeout_precommits


@RULES
@given(f=fs, order_seed=seeds)
def test_l47_negative_sub_quorum(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Precommit(height=1, round=0, value=val(5), sender=s)
            for s in senders(2 * f)]
    deliver(proc, msgs, order_seed)
    assert rec.timeout_precommits == []


# --------------------------------------------- L49 commit upon 2f+1 match


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_l49_commit_fires_once_and_advances_height(f, value, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    msgs += [Precommit(height=1, round=0, value=value, sender=s)
             for s in senders(2 * f + 1)]
    deliver(proc, msgs, order_seed)
    assert rec.commits == [(1, value)]
    assert proc.current_height == 2
    assert proc.current_round == 0
    assert proc.state.locked_round == INVALID_ROUND
    assert proc.state.prevote_logs == {} and proc.state.precommit_logs == {}


@RULES
@given(f=fs, value=good_values, order_seed=seeds, nil_votes=st.booleans())
def test_l49_negative_no_commit_without_value_quorum(
    f, value, order_seed, nil_votes
):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    who = senders(2 * f + 1)
    if nil_votes:
        # quorum of NIL precommits: no commit ever.
        msgs += [Precommit(height=1, round=0, value=NIL_VALUE, sender=s)
                 for s in who]
    else:
        # 2f+1 precommits, no value at quorum.
        msgs += [Precommit(height=1, round=0,
                           value=value if i < f else val(0x40 + i), sender=s)
                 for i, s in enumerate(who)]
    deliver(proc, msgs, order_seed)
    assert rec.commits == []
    assert proc.current_height == 1


# ------------------------------------------------- L55 future-round skip
#
# The reference's negative-case matrix (process_test.go:3279-3803): the
# skip needs f+1 UNIQUE signatories, all with messages in the SAME round,
# and that round strictly ahead of the current one.


@RULES
@given(f=fs, r=st.integers(min_value=1, max_value=64), order_seed=seeds,
       kinds=st.lists(st.integers(0, 1), min_size=4, max_size=4))
def test_l55_f_plus_one_unique_senders_skip(f, r, order_seed, kinds):
    proc, rec = make_process(f=f)
    proc.start()
    who = senders(f + 1)
    msgs = []
    for i, s in enumerate(who):
        if kinds[i % len(kinds)]:
            msgs.append(Prevote(height=1, round=r, value=val(6), sender=s))
        else:
            msgs.append(Precommit(height=1, round=r, value=val(6), sender=s))
    deliver(proc, msgs, order_seed)
    assert proc.current_round == r
    assert proc.current_step == Step.PROPOSING


@RULES
@given(f=fs, r=st.integers(min_value=1, max_value=64), order_seed=seeds)
def test_l55_negative_duplicate_senders_do_not_skip(f, r, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    who = senders(f)  # f unique; one sends twice (a prevote and a precommit)
    msgs = [Prevote(height=1, round=r, value=val(6), sender=s) for s in who]
    msgs.append(Precommit(height=1, round=r, value=val(7), sender=who[0]))
    deliver(proc, msgs, order_seed)
    assert proc.current_round == 0


@RULES
@given(f=fs, order_seed=seeds)
def test_l55_negative_votes_spread_across_rounds_do_not_skip(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    # f+1 unique senders but each in a DIFFERENT future round.
    msgs = [Prevote(height=1, round=1 + i, value=val(6), sender=s)
            for i, s in enumerate(senders(f + 1))]
    deliver(proc, msgs, order_seed)
    assert proc.current_round == 0


@RULES
@given(f=fs, order_seed=seeds)
def test_l55_negative_current_round_votes_do_not_skip(f, order_seed):
    proc, rec = make_process(f=f)
    proc.start()
    msgs = [Prevote(height=1, round=0, value=val(6), sender=s)
            for s in senders(f + 1)]
    deliver(proc, msgs, order_seed)
    assert proc.current_round == 0


# ------------------------------------------------------------ equivocation


@RULES
@given(f=fs, value=good_values, same=st.booleans())
def test_double_prevote_catching(f, value, same):
    proc, rec = make_process(f=f)
    proc.start()
    first = Prevote(height=1, round=0, value=value, sender=sig(20))
    # A guaranteed-different value: flip one byte of the drawn one.
    other = value[:-1] + bytes([value[-1] ^ 1])
    second = first if same else Prevote(height=1, round=0, value=other,
                                        sender=sig(20))
    proc.prevote(first)
    proc.prevote(second)
    if same:
        assert rec.double_prevotes == []
    else:
        assert rec.double_prevotes == [(second, first)]
    # The log always keeps the FIRST message.
    assert proc.state.prevote_logs[0][sig(20)] == first


@RULES
@given(f=fs, value=good_values, same=st.booleans())
def test_double_precommit_catching(f, value, same):
    proc, rec = make_process(f=f)
    proc.start()
    first = Precommit(height=1, round=0, value=value, sender=sig(21))
    other = value[:-1] + bytes([value[-1] ^ 1])
    second = first if same else Precommit(height=1, round=0, value=other,
                                          sender=sig(21))
    proc.precommit(first)
    proc.precommit(second)
    assert rec.double_precommits == ([] if same else [(second, first)])
    assert proc.state.precommit_logs[0][sig(21)] == first


# --------------------------------------- whole-round interleaving invariance


@RULES
@given(f=fs, value=good_values, order_seed=seeds)
def test_full_round_commits_under_any_interleaving(f, value, order_seed):
    """A complete honest round's traffic — propose, 2f+1 prevotes, 2f+1
    precommits — must commit the proposed value no matter the delivery
    order (the retry cascade + once-flags make rule firing order-
    insensitive)."""
    proc, rec = make_process(f=f)
    proc.start()
    who = senders(2 * f + 1)
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    msgs += [Prevote(height=1, round=0, value=value, sender=s) for s in who]
    msgs += [Precommit(height=1, round=0, value=value, sender=s) for s in who]
    deliver(proc, msgs, order_seed)
    assert rec.commits == [(1, value)]
    assert proc.current_height == 2


@RULES
@given(f=fs, value=good_values, order_seed=seeds,
       drop=st.integers(min_value=0, max_value=6))
def test_partial_round_never_commits_wrong_value(f, value, order_seed, drop):
    """Dropping an arbitrary message from the full round can stall the
    commit but can never commit a different value or fork the height."""
    proc, rec = make_process(f=f)
    proc.start()
    who = senders(2 * f + 1)
    msgs = [Propose(height=1, round=0, valid_round=INVALID_ROUND,
                    value=value, sender=PROPOSER)]
    msgs += [Prevote(height=1, round=0, value=value, sender=s) for s in who]
    msgs += [Precommit(height=1, round=0, value=value, sender=s) for s in who]
    del msgs[drop % len(msgs)]
    deliver(proc, msgs, order_seed)
    assert rec.commits in ([], [(1, value)])


# --------------------------------------------------------- serde properties


@SERDE
@given(seed=seeds)
def test_process_checkpoint_round_trip_random_states(seed):
    rng = random.Random(seed)
    proc, _ = make_process()
    proc.state = random_state(rng)
    w = Writer()
    proc.marshal(w)
    restored, _ = make_process()
    restored.unmarshal_into(Reader(w.data()))
    assert restored.state == proc.state
    assert restored.whoami == proc.whoami
    assert restored.f == proc.f


@SERDE
@given(seed=seeds)
def test_message_envelope_round_trip_random_messages(seed):
    rng = random.Random(seed)
    for gen in (random_propose, random_prevote, random_precommit):
        msg = gen(rng)
        try:
            w = Writer()
            marshal_message(msg, w)
        except SerdeError:
            continue  # out-of-range draws may legitimately refuse to marshal
        back = unmarshal_message(Reader(w.data()))
        assert back == msg


@SERDE
@given(blob=st.binary(min_size=0, max_size=256))
def test_unmarshal_fuzz_never_crashes(blob):
    """Garbage bytes must raise SerdeError (or parse), never anything else
    (reference contract: process_test.go:22-31)."""
    try:
        unmarshal_message(Reader(blob))
    except SerdeError:
        pass
    proc, _ = make_process()
    try:
        proc.unmarshal_into(Reader(blob))
    except SerdeError:
        pass


@SERDE
@given(seed=seeds, budget=st.integers(min_value=0, max_value=40))
def test_undersized_budget_errors_cleanly(seed, budget):
    rng = random.Random(seed)
    proc, _ = make_process()
    proc.state = random_state(rng)
    w = Writer()
    proc.marshal(w)
    data = w.data()
    if budget >= len(data):
        return
    restored, _ = make_process()
    try:
        restored.unmarshal_into(Reader(data, rem=budget))
    except SerdeError:
        pass
    else:
        raise AssertionError("undersized budget must error")


# --------------------------------------------------- batched window ingestion


@RULES
@given(
    data=st.lists(
        st.tuples(
            st.sampled_from(["propose", "prevote", "precommit"]),
            st.integers(min_value=0, max_value=2),  # round
            st.integers(min_value=2, max_value=5),  # sender tag
            st.booleans(),  # nil vote?
        ),
        min_size=1,
        max_size=30,
    )
)
def test_batched_ingest_matches_serial_delivery(data):
    """Process.ingest(window) must reach the same commits and height as
    per-message delivery of the same window in its given order — the
    equivalence the batched driving mode (burst batch_ingest) rests on.
    Single candidate value, so conflicting cross-round quorums (which
    legitimately order-depend) cannot arise."""
    V = val(7)

    def build(kind, rnd, snd, nil):
        if kind == "propose":
            return Propose(height=1, round=rnd, valid_round=INVALID_ROUND,
                           value=V, sender=PROPOSER)
        cls = Prevote if kind == "prevote" else Precommit
        return cls(height=1, round=rnd, value=NIL_VALUE if nil else V,
                   sender=sig(snd))

    msgs = [build(*t) for t in data]

    serial, rec_s = make_process()
    serial.start()
    for m in msgs:
        if isinstance(m, Propose):
            serial.propose(m)
        elif isinstance(m, Prevote):
            serial.prevote(m)
        else:
            serial.precommit(m)

    batched, rec_b = make_process()
    batched.start()
    batched.ingest(list(msgs))

    assert rec_b.commits == rec_s.commits
    assert batched.current_height == serial.current_height
    # Round advance happens only via commit (both then restart at 0) or the
    # trace-log skip, whose maximal qualifying round depends only on the
    # final logs — identical between the two modes.
    assert batched.state.current_round == serial.state.current_round
    # Liveness parity on the round both ended in: the L47 timeout for the
    # FINAL round is scheduled by both or neither. Intermediate rounds
    # legitimately differ (serial may pass through rounds the batched
    # maximal skip jumps over); those timeouts' fire-time guards no-op.
    if not rec_b.commits:
        final = (1, batched.state.current_round)
        assert (final in rec_b.timeout_precommits) == (
            final in rec_s.timeout_precommits
        )


# ----------------------------------------------------------- lock discipline


@RULES
@given(
    plan=st.lists(st.booleans(), min_size=1, max_size=4),
)
def test_lock_discipline_across_rounds(plan):
    """Once locked, the automaton NEVER prevotes a conflicting fresh
    value in any later round (safety half of the locking rules); it
    prevotes the locked value again exactly when the proposal re-carries
    it with a valid_round the lock permits. ``plan[r]`` chooses what the
    round-(r+1) proposer offers: True = re-propose the locked value with
    valid_round=0, False = a fresh conflicting value."""
    locked = val(1)
    proc, rec = make_process()
    proc.start()
    # Lock at round 0: valid proposal + 2f+1 prevotes while prevoting.
    proc.propose(Propose(height=1, round=0, valid_round=INVALID_ROUND,
                         value=locked, sender=PROPOSER))
    for i in (3, 4, 5):
        proc.prevote(Prevote(height=1, round=0, value=locked, sender=sig(i)))
    assert proc.state.locked_round == 0

    for r, repropose in enumerate(plan, start=1):
        proc.on_timeout_precommit(1, r - 1)
        assert proc.state.current_round == r
        if repropose:
            proc.propose(Propose(height=1, round=r, valid_round=0,
                                 value=locked, sender=PROPOSER))
            assert rec.prevotes[-1].value == locked
            assert rec.prevotes[-1].round == r
        else:
            proc.propose(Propose(height=1, round=r, valid_round=INVALID_ROUND,
                                 value=val(2 + r), sender=PROPOSER))
            assert rec.prevotes[-1].value == NIL_VALUE
            assert rec.prevotes[-1].round == r
        # The lock itself never moves (no newer quorum in this history).
        assert proc.state.locked_value == locked
        assert proc.state.locked_round == 0
