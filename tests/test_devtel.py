"""Device-pipeline telemetry: launch probes, registry, tenant latency.

Jax-free unit coverage for obs/devtel.py + obs/metrics.py and their
wiring through the device work queue and the multi-tenant verify
service — all on an injected fake clock, so every asserted number is
exact, never "close enough".
"""

import threading

import pytest

from hyperdrive_tpu.analysis.annotations import device_fetch, set_fetch_probe
from hyperdrive_tpu.devsched import DeviceWorkQueue
from hyperdrive_tpu.obs.devtel import (
    NULL_DEVTEL,
    DeviceTelemetry,
    NullDeviceTelemetry,
)
from hyperdrive_tpu.obs.metrics import (
    Registry,
    histogram_stats,
    merge_histograms,
    to_prometheus,
)
from hyperdrive_tpu.obs.recorder import EVENT_KINDS, Recorder
from hyperdrive_tpu.obs.report import tenant_summary
from hyperdrive_tpu.utils.trace import Histogram, Tracer
from hyperdrive_tpu.verifier import NullVerifier


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


class EchoLauncher:
    kind = "echo"

    def __init__(self):
        self.launches = []

    def launch(self, payloads):
        self.launches.append([len(p) for p in payloads])
        return [list(p) for p in payloads]


def probed_queue(clock=None):
    clock = clock or FakeClock()
    rec = Recorder(capacity=256, time_fn=clock)
    devtel = DeviceTelemetry(
        recorder=rec, registry=Registry(time_fn=clock), time_fn=clock
    )
    return DeviceWorkQueue(devtel=devtel), devtel, rec, clock


# --------------------------------------------------------- queue probe


def test_drain_produces_attributed_launch_record():
    q, devtel, rec, clock = probed_queue()
    launcher = EchoLauncher()
    f1 = q.submit(launcher, [1, 2, 3], origin=0, rows=3)
    clock.now = 1.0
    f2 = q.submit(launcher, [4], origin=5, rows=1)
    clock.now = 2.5
    q.drain()

    assert (f1.seq, f2.seq) == (0, 1)
    assert f1.launch_id == f2.launch_id == 0
    [lr] = devtel.records
    assert lr.kind == "echo"
    assert lr.commands == 2 and lr.rows == 4
    assert lr.lanes == 4 and lr.occupancy_pct == 100  # no bucket ladder
    assert lr.queue_wait_max == pytest.approx(2.5)  # f1 waited 0.0 -> 2.5
    assert lr.queue_wait_sum == pytest.approx(2.5 + 1.5)
    assert lr.origins == (0, 5)
    d = lr.as_dict()
    assert d["launch_id"] == 0 and d["rows"] == 4

    kinds = [e.kind for e in rec.snapshot()]
    assert kinds == [
        "sched.launch.submit", "sched.launch.submit",
        "sched.launch.begin", "sched.launch.cmd", "sched.launch.cmd",
        "sched.launch.rows", "sched.launch.lanes",
        "sched.launch.occupancy", "sched.launch.queue_wait",
        "sched.launch.end",
    ]
    # Submit events ride the submitter's track; the launch rides -2.
    submits = [e for e in rec.snapshot() if e.kind == "sched.launch.submit"]
    assert [e.replica for e in submits] == [0, 5]
    # queue_wait journal detail is integer microseconds.
    [qw] = [e for e in rec.snapshot() if e.kind == "sched.launch.queue_wait"]
    assert qw.detail == 2_500_000

    snap = devtel.registry.snapshot()
    assert snap["counters"]["devtel.submitted"] == 2
    assert snap["counters"]["devtel.launches"] == 1
    assert snap["counters"]["devtel.launch.rows"] == 4
    assert snap["gauges"]["devtel.launch.last_id"] == 0
    assert snap["histograms"]["devtel.launch.coalesce"]["count"] == 1
    assert snap["histograms"]["devtel.launch.queue_wait.latency"][
        "p50"
    ] == pytest.approx(2.5)


def test_generation_split_emits_and_counts():
    q, devtel, rec, _ = probed_queue()
    launcher = EchoLauncher()
    q.submit(launcher, [1], generation=0, origin=0, rows=1)
    q.submit(launcher, [2], generation=1, origin=0, rows=1)
    q.drain()
    assert len(devtel.records) == 2
    assert [lr.generation for lr in devtel.records] == [0, 1]
    splits = [e for e in rec.snapshot() if e.kind == "sched.launch.split"]
    assert [e.detail for e in splits] == [1]
    snap = devtel.registry.snapshot()
    assert snap["counters"]["devtel.launch.gen_splits"] == 1


def test_lanes_resolve_from_bucket_ladder():
    q, devtel, _, _ = probed_queue()

    class LadderedVerifier:
        buckets = (4, 8, 16)

        def verify_signatures(self, items):
            return [True] * len(items)

    launcher = q.verify_launcher(LadderedVerifier())
    q.submit(launcher, [(b"\x00" * 32, b"\x01" * 32, None)] * 5,
             origin=0, rows=5)
    q.drain()
    [lr] = devtel.records
    assert lr.rows == 5 and lr.lanes == 8  # padded to the 8-lane bucket
    assert lr.occupancy_pct == 62


def test_fetch_probe_attributes_sync_time_inside_launch():
    clock = FakeClock()
    devtel = DeviceTelemetry(registry=Registry(time_fn=clock),
                             time_fn=clock)

    class FetchingLauncher:
        kind = "fetching"

        def launch(self, payloads):
            clock.now += 0.25  # dispatch work
            device_fetch([1, 2, 3], why="test sync")
            clock.now += 0.5  # more dispatch after the sync
            return [list(p) for p in payloads]

    # The annotations-module fetch probe only times the bracket when a
    # launch is open, so wrap through the queue.
    q = DeviceWorkQueue(devtel=devtel)
    q.submit(FetchingLauncher(), [7], origin=0, rows=1)

    # Make the fetch itself cost 0.125 virtual seconds.
    orig_begin = devtel.fetch_begin

    def slow_begin(why):
        orig_begin(why)
        clock.now += 0.125

    devtel.fetch_begin = slow_begin
    q.drain()
    [lr] = devtel.records
    assert lr.syncs == 1
    assert lr.t_sync == pytest.approx(0.125)
    # Dispatch excludes the sync share it bracketed.
    assert lr.t_dispatch == pytest.approx(0.75)
    assert lr.wall == pytest.approx(0.875)
    # Probe uninstalled after the drain: raw fetches no longer tap it.
    device_fetch([1], why="outside launch")
    assert devtel.records[-1].syncs == 1


def test_launcher_exception_still_seals_record_and_probe():
    q, devtel, rec, _ = probed_queue()

    class Boom:
        kind = "boom"

        def launch(self, payloads):
            raise RuntimeError("device fell over")

    q.submit(Boom(), [1], origin=0, rows=1)
    with pytest.raises(RuntimeError, match="fell over"):
        q.drain()
    assert len(devtel.records) == 1  # sealed on the error path
    assert any(e.kind == "sched.launch.end" for e in rec.snapshot())
    from hyperdrive_tpu.analysis import annotations

    assert annotations._fetch_probe is None


def test_null_devtel_is_inert_and_default():
    q = DeviceWorkQueue()
    assert q.devtel is NULL_DEVTEL
    fut = q.submit(EchoLauncher(), [1, 2])
    q.drain()
    assert fut.seq is None and fut.launch_id is None
    assert isinstance(NULL_DEVTEL, NullDeviceTelemetry)
    assert NULL_DEVTEL.command(0, 3) is None
    assert NULL_DEVTEL.launch_begin("echo", 0, []) is None


def test_devtel_event_kinds_are_in_taxonomy():
    for k in (
        "sched.launch.submit", "sched.launch.begin", "sched.launch.cmd",
        "sched.launch.rows", "sched.launch.lanes",
        "sched.launch.occupancy", "sched.launch.queue_wait",
        "sched.launch.split", "sched.launch.end", "sched.launch.commit",
        "verify.occupancy.rows", "verify.occupancy.lanes",
        "verify.occupancy.pct", "metrics.snapshot",
    ):
        assert k in EVENT_KINDS, k


# ------------------------------------------------------- tenant service


def test_shard_service_attributes_per_tenant_latency():
    from hyperdrive_tpu.parallel.multihost import ShardVerifyService

    clock = FakeClock()
    devtel = DeviceTelemetry(registry=Registry(time_fn=clock),
                             time_fn=clock)
    svc = ShardVerifyService(NullVerifier(), devtel=devtel)
    rows = [(b"\x00" * 32, b"\x01" * 32, None)]
    svc.submit("tenant-a", rows * 2)
    clock.now = 0.5
    svc.submit("tenant-b", rows * 3)
    clock.now = 2.0
    svc.drain()

    assert svc.tenant_ids == {"tenant-a": 0, "tenant-b": 1}
    snap = devtel.registry.snapshot()
    lat = snap["histograms"]["tenant.verify.latency"]
    assert set(lat) == {"0", "1"}
    assert lat["0"]["p50"] == pytest.approx(2.0)
    assert lat["1"]["p50"] == pytest.approx(1.5)
    # The launch record carries both tenants' origins.
    assert devtel.records[-1].origins == (0, 1)


def test_tenant_summary_reconstructs_from_journal():
    q, devtel, rec, clock = probed_queue()
    launcher = EchoLauncher()
    q.submit(launcher, [1, 2], origin=0, rows=2)
    clock.now = 1.0
    q.submit(launcher, [3], origin=1, rows=1)
    clock.now = 3.0
    q.drain()
    # A gated commit finalized off that launch, 1s after the drain.
    clock.now = 4.0
    rec.emit("sched.launch.commit", 2, 9, -1, 0)

    rows = tenant_summary(rec.snapshot())
    by = {r["tenant"]: r for r in rows}
    assert set(by) == {0, 1}
    assert by[0]["submits"] == 1 and by[0]["launches"] == 1
    assert by[0]["verify_p50_s"] == pytest.approx(3.0)
    assert by[1]["verify_p50_s"] == pytest.approx(2.0)
    assert by[0]["commit_p50_s"] == pytest.approx(4.0)
    assert by[1]["commit_p50_s"] == pytest.approx(3.0)
    assert by[0]["commits"] == 1


# ------------------------------------------------------------- registry


def test_registry_counters_gauges_histograms_and_labels():
    clock = FakeClock()
    reg = Registry(time_fn=clock)
    reg.count("a.b", 3)
    reg.count("a.b")
    reg.set_gauge("g.depth", 7)
    reg.observe("h.lat", 0.5)
    reg.observe("h.lat", 1.5)
    reg.count("t.per", 2, label="x")
    reg.observe("t.lat", 0.25, label="x")
    with reg.span("s.lat"):
        clock.now += 2.0
    snap = reg.snapshot()
    assert snap["counters"]["a.b"] == 4
    assert snap["counters"]["t.per"] == {"x": 2}
    assert snap["gauges"]["g.depth"] == 7
    assert snap["histograms"]["h.lat"]["count"] == 2
    assert snap["histograms"]["h.lat"]["mean"] == pytest.approx(1.0)
    assert snap["histograms"]["t.lat"]["x"]["count"] == 1
    assert snap["histograms"]["s.lat"]["p50"] == pytest.approx(2.0)


def test_registry_digest_is_deterministic_and_sensitive():
    a, b = Registry(time_fn=lambda: 0.0), Registry(time_fn=lambda: 0.0)
    for reg in (a, b):
        reg.count("x.y", 2)
        reg.observe("z.lat", 1.0)
    assert a.digest() == b.digest()
    b.count("x.y")
    assert a.digest() != b.digest()


def test_registry_merge_adds_counters_and_merges_histograms():
    a, b = Registry(), Registry()
    a.count("c", 1)
    b.count("c", 2)
    a.observe("h", 1.0)
    b.observe("h", 3.0)
    b.set_gauge("g", 9)
    a.count("lc", 1, label="t0")
    b.count("lc", 4, label="t0")
    b.observe("lh", 2.0, label="t1")
    a.merge(b)
    snap = a.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["counters"]["lc"] == {"t0": 5}
    assert snap["gauges"]["g"] == 9
    assert snap["histograms"]["h"]["count"] == 2
    assert snap["histograms"]["lh"]["t1"]["count"] == 1


def test_merge_histograms_is_exact_and_rejects_ladder_mismatch():
    a, b = Histogram(), Histogram()
    for v in (0.1, 0.2):
        a.observe(v)
    for v in (0.3, 0.4, 0.5):
        b.observe(v)
    m = merge_histograms(a, b)
    assert m.total == 5
    assert m.sum == pytest.approx(1.5)
    assert [x + y for x, y in zip(a.counts, b.counts)] == m.counts
    with pytest.raises(ValueError, match="buckets"):
        merge_histograms(a, Histogram(buckets=(1.0, 2.0)))


def test_absorb_tracer_shares_objects_by_reference():
    reg = Registry()
    tracer = Tracer(threadsafe=False)
    tracer.count("sim.step", 5)
    tracer.observe("sim.lat", 0.5)
    reg.absorb_tracer(tracer)
    tracer.count("sim.step", 2)  # updates after absorb are visible
    snap = reg.snapshot()
    assert snap["counters"]["sim.step"] == 7
    assert snap["histograms"]["sim.lat"]["count"] == 1


def test_to_prometheus_renders_all_shapes():
    reg = Registry()
    reg.count("req.total", 3)
    reg.count("req.by", 1, label="a b")
    reg.set_gauge("depth", 2)
    reg.observe("lat.s", 0.5)
    reg.observe("lat.by", 0.25, label="t0")
    text = to_prometheus(reg.snapshot())
    assert "# TYPE hd_req_total counter" in text
    assert "hd_req_total 3" in text
    assert 'hd_req_by{label="a b"} 1' in text
    assert "# TYPE hd_depth gauge" in text
    assert "# TYPE hd_lat_s summary" in text
    assert 'hd_lat_s{quantile="50"} 0.5' in text
    assert "hd_lat_s_count 1" in text
    assert 'hd_lat_by{label="t0",quantile="95"} 0.25' in text
    assert text.endswith("\n")


def test_histogram_stats_keys():
    h = Histogram()
    h.observe(1.0)
    row = histogram_stats(h)
    assert set(row) == {"count", "sum", "mean", "p50", "p95", "p99"}


# --------------------------------------------- recorder dropped (threads)


def test_threaded_emits_keep_total_dropped_len_consistent():
    # The satellite spec for the Recorder.dropped atomicity fix: many
    # writer threads hammering a tiny ring must never lose or double
    # count a drop — total == len + dropped exactly, under the lock.
    rec = Recorder(capacity=32, threadsafe=True)
    n_threads, per_thread = 8, 500

    def hammer(i):
        bound = rec.scoped(i)
        for j in range(per_thread):
            bound.emit("commit", j, 0)

    threads = [
        threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert rec.total == total
    assert len(rec) == 32
    assert rec.dropped == total - 32
    # Snapshot under the same lock: a consistent, fully-formed window.
    snap = rec.snapshot()
    assert len(snap) == 32
    assert all(e.kind == "commit" for e in snap)
