"""Verification drive: embed hyperdrive_tpu as an application would.

Builds a 4-replica in-process network with a global FIFO message queue
(the way the reference's replica_test harness wires Broadcaster/Timer),
runs consensus to height 5, and checks every replica committed the
identical chain. Then probes: Byzantine out-of-turn proposer, garbage
unmarshal, checkpoint/restore mid-flight.
"""

import hashlib
import random

from hyperdrive_tpu.messages import Timeout
from hyperdrive_tpu.replica import Replica, ReplicaOptions
from hyperdrive_tpu.testutil import (
    BroadcasterCallbacks,
    CatcherCallbacks,
    CommitterCallback,
    MockProposer,
    MockValidator,
    TimerCallbacks,
)

N = 4
TARGET = 5
rng = random.Random(42)
keys = [hashlib.sha256(f"replica-{i}".encode()).digest() for i in range(N)]

global_q = []   # (to, msg) — broadcast appends to every replica
commits = {i: {} for i in range(N)}
caught = []


def make_replica(i):
    whoami = keys[i]

    def bcast(msg):
        for j in range(N):
            global_q.append((j, msg))

    broadcaster = BroadcasterCallbacks(
        on_propose=bcast, on_prevote=bcast, on_precommit=bcast
    )
    committer = CommitterCallback(
        on_commit=lambda h, v: (commits[i].__setitem__(h, v), (0, None))[1]
    )
    timer = TimerCallbacks()  # no timeouts needed on the happy path
    proposer = MockProposer(
        fn=lambda h, r: hashlib.sha256(f"block-{h}".encode()).digest()
    )
    catcher = CatcherCallbacks(
        on_out_of_turn_propose=lambda p: caught.append(("out_of_turn", i))
    )
    return Replica(
        ReplicaOptions(),
        whoami,
        list(keys),
        timer,
        proposer,
        MockValidator(ok=True),
        committer,
        catcher,
        broadcaster,
    )


replicas = [make_replica(i) for i in range(N)]
for r in replicas:
    r.start()

steps = 0
while global_q and steps < 100_000:
    to, msg = global_q.pop(0)
    replicas[to].handle(msg)
    steps += 1
    if all(len(commits[i]) >= TARGET for i in range(N)):
        break

heights = [r.current_height() for r in replicas]
print(f"steps={steps} heights={heights}")
assert all(h >= TARGET + 1 for h in heights), f"stalled: {heights}"
for h in range(1, TARGET + 1):
    vals = {commits[i][h] for i in range(N)}
    assert len(vals) == 1, f"SAFETY VIOLATION at height {h}: {vals}"
print(f"PASS: {N} replicas committed identical chain to height {TARGET}")

# --- probe 1: Byzantine out-of-turn proposer is caught and ignored -----
from hyperdrive_tpu.messages import Propose

bad = Propose(height=replicas[0].current_height(), round=0, valid_round=-1,
              value=b"\xee" * 32, sender=keys[3])
expected = replicas[0].proc.scheduler.schedule(bad.height, 0)
if expected != keys[3]:
    replicas[0].handle(bad)
    assert ("out_of_turn", 0) in caught, "out-of-turn propose not caught"
    print("PASS: out-of-turn propose caught by catcher")

# --- probe 2: garbage bytes never crash the codec ----------------------
from hyperdrive_tpu.codec import Reader, SerdeError
from hyperdrive_tpu.state import State

crashes = 0
for _ in range(200):
    try:
        State.unmarshal(Reader(rng.randbytes(rng.randint(0, 80))))
    except SerdeError:
        pass
    except Exception as e:
        crashes += 1
print(f"PASS: 200 garbage unmarshals, {crashes} non-SerdeError crashes" if crashes == 0
      else f"FAIL: {crashes} crashes")
assert crashes == 0

# --- probe 3: checkpoint mid-flight, restore, keep committing ----------
from hyperdrive_tpu.codec import Writer

w = Writer()
replicas[1].proc.marshal(w)
blob = w.data()
h_before = replicas[1].current_height()

# Restore into a brand-new replica object and drive the whole network on.
fresh = make_replica(1)
fresh.proc.unmarshal_into(Reader(blob))
assert fresh.current_height() == h_before
replicas[1] = fresh
global_q.clear()
for r in replicas:
    r.proc.start_round(r.proc.current_round)  # re-arm the current round
steps2 = 0
target2 = h_before + 3
while global_q and steps2 < 100_000:
    to, msg = global_q.pop(0)
    replicas[to].handle(msg)
    steps2 += 1
    if all(r.current_height() >= target2 for r in replicas):
        break
hs = [r.current_height() for r in replicas]
assert all(h >= target2 for h in hs), f"restored network stalled: {hs}"
for h in range(h_before, target2):
    vals = {commits[i][h] for i in range(N)}
    assert len(vals) == 1, f"SAFETY VIOLATION post-restore at {h}: {vals}"
print(f"PASS: restored replica at height {h_before} ({len(blob)} bytes), "
      f"network re-committed to height {target2 - 1}")

# --- probe 4: wrong-height flood is filtered, queue stays bounded ------
from hyperdrive_tpu.messages import Prevote

r0 = replicas[0]
for k in range(2000):
    r0.handle(Prevote(height=10_000 + k, round=0, value=b"\x01" * 32,
                      sender=keys[2]))
qlen = len(r0.mq)
assert qlen <= 1000, f"queue exceeded capacity: {qlen}"
print(f"PASS: far-future flood bounded at {qlen} <= 1000 (capacity eviction)")

# --- probe 5: harness scenario with reorder + replay round-trip --------
from hyperdrive_tpu.harness import ScenarioRecord, Simulation
import tempfile, os

sim = Simulation(n=10, target_height=10, seed=99, reorder=True)
res = sim.run()
assert res.completed, f"harness stalled at {res.heights}"
res.assert_safety()
with tempfile.TemporaryDirectory() as d:
    p = os.path.join(d, "failure.dump")
    res.record.dump(p)
    replayed = Simulation.replay(ScenarioRecord.load(p))
    assert replayed.commits == res.commits
print(f"PASS: harness 10-replica reorder run to height 10 in {res.steps} steps "
      f"({res.virtual_time:.1f}s virtual), dump+replay identical")

# --- probe 6: signed consensus end-to-end (Ed25519 host path) ----------
sim = Simulation(n=4, target_height=3, seed=101, sign=True)
res = sim.run()
assert res.completed, f"signed run stalled at {res.heights}"
res.assert_safety()
print(f"PASS: Ed25519-signed 4-replica consensus to height 3 "
      f"({res.steps} verified deliveries)")

# --- probe 7: TPU/device batch verifier in the consensus loop ----------
# (runs on whatever backend this process has; tests force CPU, a bare
# invocation uses the real chip)
from hyperdrive_tpu.ops.ed25519_jax import TpuBatchVerifier

shared = TpuBatchVerifier(buckets=(64,))
sim = Simulation(n=4, target_height=2, seed=202, sign=True,
                 verifier_for=lambda i: shared)
res = sim.run()
assert res.completed, f"device-verified run stalled at {res.heights}"
res.assert_safety()
print(f"PASS: consensus with batched device verifier to height 2 "
      f"({res.steps} deliveries)")

# --- probe 8: device vote-grid tallies feeding the rule cascade --------
# Quorum counts come from masked reductions over device-resident vote
# tensors; CheckedTallyView raises on any device/host count divergence.
from hyperdrive_tpu.ops.votegrid import CheckedTallyView

host_run = Simulation(n=7, target_height=4, seed=303, burst=True).run()
grid_run = Simulation(n=7, target_height=4, seed=303, burst=True,
                      device_tally=True,
                      tally_check=CheckedTallyView).run()
assert grid_run.completed, f"device-tally run stalled at {grid_run.heights}"
grid_run.assert_safety()
assert grid_run.commits == host_run.commits
print(f"PASS: device vote-grid tallies drove consensus to height 4, "
      f"count-identical to host tallies ({grid_run.steps} steps)")

# --- probe 9: deployment flush + flight record/replay ------------------
# The round-5 deployment composition, embedded the way a node would run
# it: a replica whose quorum counts come from its own n=1 device vote
# grid (DeviceTallyFlusher behind the flusher seam), every consumed
# input flight-recorded, then the log replayed into a fresh replica
# offline — commit chains identical.
from hyperdrive_tpu.tallyflush import DeviceTallyFlusher
from hyperdrive_tpu.transport import FlightRecorder, replay_flight
from hyperdrive_tpu.types import INVALID_ROUND
from hyperdrive_tpu.verifier import NullVerifier

_SIGS = [bytes([i + 1]) * 32 for i in range(4)]
_val = lambda h, r: hashlib.sha256(b"dep-%d-%d" % (h, r)).digest()


class _Loop:
    def broadcast_propose(self, m):
        self.rep.handle(m)
    broadcast_prevote = broadcast_precommit = broadcast_propose


def _dep_replica(commits, flusher=None, recorder=None):
    lb = _Loop()
    rep = Replica(
        ReplicaOptions(), whoami=_SIGS[0], signatories=list(_SIGS),
        timer=None, proposer=MockProposer(fn=_val),
        validator=MockValidator(ok=True),
        committer=CommitterCallback(
            on_commit=lambda h, v: (commits.__setitem__(h, v),
                                    (0, None))[1]),
        catcher=None, broadcaster=lb if flusher is not None else None,
        verifier=NullVerifier() if flusher is None else None,
        flusher=flusher, recorder=recorder,
    )
    lb.rep = rep
    return rep

commits_live: dict = {}
fl = DeviceTallyFlusher(
    NullVerifier(), _SIGS, tally_check=CheckedTallyView,
)
rec = FlightRecorder()
live = _dep_replica(commits_live, flusher=fl, recorder=rec)
live.start()
from hyperdrive_tpu.messages import Precommit as _Pc, Prevote as _Pv, \
    Propose as _Pp
for h in (1, 2):
    v = _val(h, 0)
    proposer = live.proc.scheduler.schedule(h, 0)
    if proposer != _SIGS[0]:
        live.handle(_Pp(height=h, round=0, valid_round=INVALID_ROUND,
                        value=v, sender=proposer))
    for s in _SIGS[1:]:
        live.handle(_Pv(height=h, round=0, value=v, sender=s))
    for s in _SIGS[1:]:
        live.handle(_Pc(height=h, round=0, value=v, sender=s))
assert set(commits_live) == {1, 2}, commits_live
assert fl.launches > 0

with tempfile.TemporaryDirectory() as d:
    p = os.path.join(d, "flight.log")
    rec.dump(p)
    commits_replay: dict = {}
    replay_flight(p, _dep_replica(commits_replay))
    assert commits_replay == commits_live, "flight replay diverged"
print(f"PASS: deployment flush (n=1 device grid, {fl.launches} tally "
      f"launches, counts host-checked) committed 2 heights; flight log "
      f"replayed to an identical chain offline")
